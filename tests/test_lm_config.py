"""The unified GPT surface: config-DSL LM training == models/gpt.py, the
performance levers (remat / remat_mode / attn_layout / zero) as config
keys, the lm iterator, and task=generate through the CLI/wrapper.

Round-5 bar (VERDICT r4 #1): the flagship's features must be reachable
from the netconfig surface, pinned by equivalence against the functional
path — one framework, not two."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cxxnet_tpu import Net
from cxxnet_tpu.io.data import DataBatch
from cxxnet_tpu.models import gpt_lm_config
from cxxnet_tpu.utils.config import ConfigError, tokenize

N, B, V = 16, 8, 32


def _ids(seed=0):
    rs = np.random.RandomState(seed)
    ids = rs.randint(0, V, (B, N)).astype(np.float32)
    return ids.reshape(B, 1, 1, N), ids


def _train(cfg_kwargs, steps=3, seed=0):
    cfg = gpt_lm_config(seq_len=N, vocab_size=V, feat=16, nhead=2,
                        nblock=2, batch_size=B, **cfg_kwargs)
    net = Net(tokenize(cfg))
    net.init_model()
    data, ids = _ids(seed)
    for _ in range(steps):
        net.update(DataBatch(data, ids))
    return net


def test_lm_config_levers_match_baseline():
    """remat (both modes), attn_layout=bhnd, ZeRO-3, pp2+remat, and sp2
    all compute the same loss as the plain config — the levers are
    layout/memory choices, not semantics."""
    variants = {
        "base": {},
        "remat": dict(remat=1),
        "remat_attn_saved": dict(remat=1, remat_mode="attn_saved"),
        "bhnd": dict(attn_layout="bhnd"),
        "zero3": dict(zero=3, dev="cpu:0-7"),
        "pp2_remat": dict(pipeline_parallel=2, remat=1, dev="cpu:0-7"),
        "sp2_bhnd": dict(seq_parallel=2, attn_layout="bhnd",
                         dev="cpu:0-7"),
    }
    losses = {k: _train(kw).last_loss() for k, kw in variants.items()}
    for k, v in losses.items():
        assert abs(v - losses["base"]) < 1e-4, (k, losses)


def test_lm_config_matches_gpt_functional_path():
    """The trajectory oracle between the two surfaces: the SAME weights
    stepped by the config-DSL trainer and by models/gpt.py's
    make_train_step stay equal — per-step losses to 5e-6 and the full
    parameter trees to 5e-6 after 5 SGD steps."""
    from cxxnet_tpu.models.gpt import (gpt_loss, gpt_opt_init, gpt_place,
                                       make_train_step)
    from cxxnet_tpu.nnet.lm import net_gpt_config, net_to_gpt_params
    from cxxnet_tpu.parallel.mesh import make_mesh

    cfg = gpt_lm_config(seq_len=N, vocab_size=V, feat=16, nhead=2,
                        nblock=3, batch_size=B, dev="cpu:0", eta=0.1)
    net = Net(tokenize(cfg))
    net.init_model()
    gcfg = net_gpt_config(net)
    assert (gcfg.n_layer, gcfg.n_head, gcfg.feat) == (3, 2, 16)
    params = gpt_place(net_to_gpt_params(net), mesh := make_mesh("cpu:0"))
    mom = gpt_opt_init(params, mesh, "sgd")
    step = make_train_step(gcfg, mesh, eta=0.1, momentum=0.9)
    data, ids = _ids()
    ids_i = jnp.asarray(ids.astype(np.int32))
    for t in range(5):
        l_fn = float(gpt_loss(params, ids_i, gcfg, mesh))
        params, mom, _ = step(params, mom, ids_i)
        net.update(DataBatch(data, ids))
        assert abs(l_fn - net.last_loss()) < 5e-6, (t, l_fn,
                                                    net.last_loss())
    p2 = net_to_gpt_params(net)
    for a, b in zip(jax.tree.leaves(p2), jax.tree.leaves(params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-6)


def test_net_generate_greedy_matches_forward_argmax():
    """One-token greedy generation == argmax of the net's own forward
    logits at the last prompt position (the decode path's KV-cache
    prefill must agree with the training forward)."""
    from cxxnet_tpu.nnet.lm import net_generate

    net = _train({"dev": "cpu:0"}, steps=2)
    data, ids = _ids(3)
    prompt = ids[:4, :8].astype(np.int32)
    out = net_generate(net, prompt, max_new=1)
    assert out.shape == (4, 9)
    # forward the prompt padded to seq_len through the net; node 'logits'
    # is later overwritten by the lm_softmax self-loop, so probs = logits
    # argmax-wise
    padded = np.zeros((4, 1, 1, N), np.float32)
    padded[:, 0, 0, :8] = prompt
    (probs,) = net._jit_forward(net.params, net.states,
                                jnp.asarray(padded), [],
                                (net.graph.num_nodes - 1,))
    nxt = np.argmax(np.asarray(probs).reshape(4, N, V)[:, 7], axis=-1)
    np.testing.assert_array_equal(out[:, 8], nxt)


def test_generate_rejects_moe_blocks():
    from cxxnet_tpu.nnet.lm import net_generate

    cfg = gpt_lm_config(seq_len=N, vocab_size=V, feat=16, nhead=2,
                        nblock=2, batch_size=B, dev="cpu:0",
                        moe_experts=4)
    net = Net(tokenize(cfg))
    net.init_model()
    # MoE blocks carry an aux loss, so they are not even a detectable
    # dense segment — generate refuses with a precise error either way
    with pytest.raises(ConfigError,
                       match="MoE|no repeated transformer block"):
        net_generate(net, np.zeros((1, 4), np.int32), 2)


def test_remat_needs_repeated_segment():
    from cxxnet_tpu.models import alexnet_config

    net = Net(tokenize(alexnet_config(batch_size=8, dev="cpu:0")))
    net.set_param("remat", "1")
    with pytest.raises(ConfigError, match="repeated block segment"):
        net.init_model()


def test_attn_saved_needs_attention():
    """A repeated conv stack remats fine in block mode but attn_saved
    must fail loudly (no attention half to save)."""
    cfg = """
netconfig=start
layer[0->a] = conv:c0
  kernel_size = 3
  pad = 1
  nchannel = 4
layer[a->b] = conv:c1
  kernel_size = 3
  pad = 1
  nchannel = 4
layer[b->c] = conv:c2
  kernel_size = 3
  pad = 1
  nchannel = 4
layer[c->d] = conv:c3
  kernel_size = 3
  pad = 1
  nchannel = 4
layer[d->e] = flatten
layer[e->f] = fullc:fc
  nhidden = 4
layer[f->f] = softmax
netconfig=end
input_shape = 4,8,8
batch_size = 8
dev = cpu:0
remat = 1
remat_mode = attn_saved
eta = 0.1
"""
    net = Net(tokenize(cfg))
    with pytest.raises(ConfigError, match="attention"):
        net.init_model()
    net2 = Net(tokenize(cfg.replace("remat_mode = attn_saved",
                                    "remat_mode = block")))
    net2.init_model()
    assert net2._remat_segment is not None
    rs = np.random.RandomState(0)
    net2.update(DataBatch(rs.rand(8, 4, 8, 8).astype(np.float32),
                          rs.randint(0, 4, (8, 1)).astype(np.float32)))


def test_lm_iterator_windows(tmp_path):
    """Window/stride/label contract + bytes and npy formats, gz included."""
    import gzip

    from cxxnet_tpu.io import create_iterator

    toks = np.arange(40, dtype=np.uint16)
    raw = tmp_path / "toks.npy"
    np.save(raw, toks)
    it = create_iterator([("iter", "lm"), ("path_data", str(raw)),
                          ("seq_len", "8"), ("stride", "4"),
                          ("batch_size", "2")])
    it.before_first()
    assert it.next()
    b = it.value()
    assert b.data.shape == (2, 1, 1, 8) and b.label.shape == (2, 8)
    np.testing.assert_array_equal(b.data[0, 0, 0], np.arange(8))
    np.testing.assert_array_equal(b.label[1], np.arange(4, 12))

    txt = tmp_path / "corpus.txt.gz"
    with gzip.open(txt, "wb") as f:
        f.write(b"hello world, hello tpu!")
    it2 = create_iterator([("iter", "lm"), ("path_data", str(txt)),
                           ("format", "bytes"), ("seq_len", "8"),
                           ("batch_size", "1")])
    it2.before_first()
    assert it2.next()
    np.testing.assert_array_equal(
        it2.value().data[0, 0, 0].astype(np.uint8),
        np.frombuffer(b"hello wo", np.uint8))


def test_lm_nll_metric():
    from cxxnet_tpu.metrics import create_metric

    rs = np.random.RandomState(0)
    n, v = 5, 7
    probs = rs.dirichlet(np.ones(v), size=(3, n)).astype(np.float64)
    label = rs.randint(0, v, (3, n)).astype(np.float32)
    m = create_metric("lm_nll")
    m.add_eval(probs.reshape(3, -1), label)
    want = -np.log([probs[i, j, int(label[i, j + 1])]
                    for i in range(3) for j in range(n - 1)]).mean()
    assert abs(m.get() - want) < 1e-12


def test_cli_lm_train_and_generate(tmp_path, capfd):
    """The reference's config-file workflow for the GPT family: train via
    the CLI from an lm-iterator corpus, snapshot, then task=generate
    produces tokens from the snapshot (cxxnet_main.cpp:57-81 — every
    task config-reachable)."""
    from cxxnet_tpu.cli import LearnTask

    corpus = tmp_path / "corpus.bin"
    rs = np.random.RandomState(0)
    # a corpus with strong bigram structure so 2 rounds move the loss
    toks = np.tile(np.arange(16, dtype=np.uint16), 40)
    corpus.write_bytes(toks.tobytes())
    conf = tmp_path / "gpt.conf"
    cfg = gpt_lm_config(seq_len=N, vocab_size=V, feat=16, nhead=2,
                        nblock=2, batch_size=8, dev="cpu:0", eta=0.2)
    conf.write_text("""
data = train
iter = lm
    path_data = "%s"
    token_dtype = uint16
    seq_len = %d
    stride = 8
    shuffle = 1
iter = end
%s
num_round = 2
save_model = 2
model_dir = %s
""" % (corpus, N, cfg, tmp_path / "models"))
    assert LearnTask().run([str(conf)]) == 0
    err = capfd.readouterr().err
    nlls = [float(l.split("lm_nll[ids]:")[1].split()[0])
            for l in err.splitlines() if "lm_nll" in l]
    assert len(nlls) == 2 and nlls[1] < nlls[0], nlls

    prompts = tmp_path / "prompts.txt"
    prompts.write_text("0 1 2 3\n4 5 6 7\n")
    gen_out = tmp_path / "gen.txt"
    assert LearnTask().run([
        str(conf), "task=generate",
        "model_in=%s" % (tmp_path / "models" / "0002.model"),
        "prompt_file=%s" % prompts, "num_gen=6",
        "generate_out=%s" % gen_out]) == 0
    rows = [[int(t) for t in l.split()]
            for l in gen_out.read_text().splitlines()]
    assert len(rows) == 2 and all(len(r) == 10 for r in rows)
    assert rows[0][:4] == [0, 1, 2, 3]


def test_wrapper_generate():
    from cxxnet_tpu import wrapper

    cfg = gpt_lm_config(seq_len=N, vocab_size=V, feat=16, nhead=2,
                        nblock=2, batch_size=B, dev="cpu:0")
    net = wrapper.Net(cfg=cfg)
    net.init_model()
    data, ids = _ids()
    net.update(data, ids)
    out = net.generate(ids[:2, :4].astype(np.int32), max_new=3)
    assert out.shape == (2, 7)
    np.testing.assert_array_equal(out[:, :4], ids[:2, :4].astype(np.int32))


def test_remat_admits_quirk_bn_pp_does_not():
    """batch_norm admission split (round-5 review finding): remat
    recomputes over the SAME full batch (exact) so quirk-mode stateless
    BN blocks are admissible; gpipe applies blocks per MICROBATCH, which
    would silently change BN statistics, so pipelining still rejects
    them loudly."""
    from cxxnet_tpu.models import resnet_config

    cfg = resnet_config(50, batch_size=8, dev="cpu:0-7").replace(
        "moving_average = 1", "moving_average = 0")
    net = Net(tokenize(cfg + "\nremat = 1\n"))
    net.init_model()
    assert net._remat_segment is not None
    with pytest.raises(ConfigError, match="no repeated block segment"):
        Net(tokenize(cfg + "\npipeline_parallel = 2\n")).init_model()
