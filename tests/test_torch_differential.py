"""Whole-net differential oracle vs torch: per-step loss-trajectory parity.

The reference's deepest QA idea is pairtest as a whole-path check
(/root/reference/src/layer/pairtest_layer-inl.hpp:14-200: two layer
implementations run side by side every Forward/Backprop with synced
weights). The per-layer torch oracles in test_layers.py cover each op;
THIS test covers their interaction: the same conv+BN+pool+fc net is built
in cxxnet_tpu and in torch from identical initial weights, trained for 50
steps on identical batches with SGD+momentum+expdecay, and the per-step
training-loss trajectories and final weights must agree. That pins the
composition of loss-grad scaling (loss_layer_base-inl.hpp:61-63), the lr
schedule's integer-division semantics (updater/param.h:85-133), the BN
batch-stats quirk, and the update order — end to end.
"""

import numpy as np
import pytest

import jax

from cxxnet_tpu import Net
from cxxnet_tpu.io.data import DataBatch
from cxxnet_tpu.utils.config import tokenize

torch = pytest.importorskip("torch")

BATCH = 32
STEPS = 50
ETA = 0.1
MOM = 0.9
WD = 1e-4
GAMMA = 0.9
LR_STEP = 10

CONF = """
netconfig=start
layer[0->1] = conv:cv1
  kernel_size = 3
  pad = 1
  nchannel = 8
layer[1->2] = batch_norm:bn1
  eps = 1e-5
layer[2->3] = relu
layer[3->4] = max_pooling
  kernel_size = 2
  stride = 2
layer[4->5] = flatten
layer[5->6] = fullc:fc1
  nhidden = 32
layer[6->7] = relu
layer[7->8] = fullc:fc2
  nhidden = 10
layer[8->8] = softmax
netconfig=end

input_shape = 1,8,8
batch_size = %(batch)d
dev = cpu
updater = sgd
eta = %(eta)g
momentum = %(mom)g
wd = %(wd)g
lr:schedule = expdecay
lr:gamma = %(gamma)g
lr:step = %(lr_step)d
metric = error
""" % dict(batch=BATCH, eta=ETA, mom=MOM, wd=WD, gamma=GAMMA,
           lr_step=LR_STEP)


class TorchNet(torch.nn.Module):
    def __init__(self):
        super().__init__()
        self.cv1 = torch.nn.Conv2d(1, 8, 3, padding=1)
        self.bn1 = torch.nn.BatchNorm2d(8, eps=1e-5)
        self.fc1 = torch.nn.Linear(128, 32)
        self.fc2 = torch.nn.Linear(32, 10)

    def forward(self, x):
        h = torch.relu(self.bn1(self.cv1(x)))
        h = torch.nn.functional.max_pool2d(h, 2, 2, ceil_mode=True)
        h = h.flatten(1)
        return self.fc2(torch.relu(self.fc1(h)))


def _lr(step: int) -> float:
    """expdecay with the reference's continuous exponent e/lr_step
    (updater/param.h schedule 1; epoch counts update steps)."""
    return ETA * GAMMA ** (step / LR_STEP)


def _sgd_step(model, bufs, step):
    """The reference SGD update: m = mu*m - lr*(g + wd*w); w += m
    (sgd_updater-inl.hpp:25-85) — NOT torch.optim.SGD, whose momentum
    buffer accumulates the raw gradient with lr applied outside."""
    lr = _lr(step)
    with torch.no_grad():
        for name, p in model.named_parameters():
            g = p.grad + WD * p
            bufs[name] = MOM * bufs[name] - lr * g
            p += bufs[name]


def _export_weights(model, net):
    """torch -> cxxnet_tpu, with layout transforms: conv OIHW -> HWIO;
    fc1 columns reordered CHW -> HWC (the flatten layer ravels the
    NHWC activation layout)."""
    sd = {k: v.detach().numpy() for k, v in model.state_dict().items()}
    net.set_weight("cv1", "wmat", sd["cv1.weight"].transpose(2, 3, 1, 0))
    net.set_weight("cv1", "bias", sd["cv1.bias"])
    net.set_weight("bn1", "wmat", sd["bn1.weight"])
    net.set_weight("bn1", "bias", sd["bn1.bias"])
    w1 = sd["fc1.weight"].reshape(32, 8, 4, 4).transpose(0, 2, 3, 1)
    net.set_weight("fc1", "wmat", w1.reshape(32, 128))
    net.set_weight("fc1", "bias", sd["fc1.bias"])
    net.set_weight("fc2", "wmat", sd["fc2.weight"])
    net.set_weight("fc2", "bias", sd["fc2.bias"])


def _import_final(net):
    """cxxnet_tpu -> torch layouts for the final-weight comparison."""
    w1 = net.get_weight("fc1", "wmat").reshape(32, 4, 4, 8)
    return {
        "cv1.weight": net.get_weight("cv1", "wmat").transpose(3, 2, 0, 1),
        "cv1.bias": net.get_weight("cv1", "bias"),
        "bn1.weight": net.get_weight("bn1", "wmat"),
        "bn1.bias": net.get_weight("bn1", "bias"),
        "fc1.weight": w1.transpose(0, 3, 1, 2).reshape(32, 128),
        "fc1.bias": net.get_weight("fc1", "bias"),
        "fc2.weight": net.get_weight("fc2", "wmat"),
        "fc2.bias": net.get_weight("fc2", "bias"),
    }


def test_whole_net_loss_trajectory_matches_torch():
    rs = np.random.RandomState(0)
    protos = rs.randn(10, 1, 8, 8).astype(np.float32)

    def batch(i):
        r = np.random.RandomState(100 + i)
        y = r.randint(0, 10, BATCH)
        x = (protos[y] + r.randn(BATCH, 1, 8, 8) * 0.5).astype(np.float32)
        return x, y

    torch.manual_seed(7)
    model = TorchNet()
    model.train()
    bufs = {n: torch.zeros_like(p) for n, p in model.named_parameters()}

    net = Net(tokenize(CONF))
    net.init_model()
    _export_weights(model, net)

    ours, theirs = [], []
    for i in range(STEPS):
        x, y = batch(i)
        # cxxnet_tpu training loss at the CURRENT weights: forward the
        # probabilities (BN's batch-stats-at-eval quirk makes the eval
        # forward identical to the train forward here — no dropout)
        probs = net.extract_feature(DataBatch(x, y[:, None].astype(np.float32)),
                                    "top[-1]")
        probs = probs.reshape(BATCH, 10)
        ours.append(float(-np.mean(np.log(probs[np.arange(BATCH), y] + 1e-12))))
        net.update(DataBatch(x, y[:, None].astype(np.float32)))

        xt = torch.from_numpy(x)
        loss = torch.nn.functional.cross_entropy(model(xt),
                                                 torch.from_numpy(y).long())
        theirs.append(float(loss.detach()))
        model.zero_grad()
        loss.backward()
        _sgd_step(model, bufs, i)

    ours, theirs = np.asarray(ours), np.asarray(theirs)
    # the trajectories must track step-by-step (f32 drift compounds, so
    # the tolerance is looser than a single-op oracle but still tight
    # enough that any semantic mismatch — lr schedule off by one, loss
    # scale, BN mode, update order — blows through it immediately)
    np.testing.assert_allclose(ours, theirs, rtol=5e-3, atol=5e-3)
    # training must actually have progressed (the check is meaningless on
    # a flat loss)
    assert theirs[-1] < theirs[0] * 0.5, theirs

    got = _import_final(net)
    want = {k: v.detach().numpy() for k, v in model.state_dict().items()
            if k in got}
    for k in want:
        np.testing.assert_allclose(got[k], want[k], rtol=2e-3, atol=2e-3,
                                    err_msg=k)


def _adam_lr():
    return 0.002


def _torch_adam_step(model, state, step):
    """The reference Adam (adam_updater-inl.hpp:16-83): one-minus decay
    convention (decay1=0.1 == beta1=0.9), weight decay entering the
    gradient as ``grad -= wd*w`` (sign quirk), lr schedule IGNORED
    (recomputed from base lr each step) — reproduced manually."""
    d1, d2, eps = 0.1, 0.001, 1e-8
    lr = _adam_lr()
    with torch.no_grad():
        fix1 = 1.0 - (1.0 - d1) ** (step + 1)
        fix2 = 1.0 - (1.0 - d2) ** (step + 1)
        lr_t = lr * (fix2 ** 0.5) / fix1
        for name, p in model.named_parameters():
            g = p.grad - WD * p                   # reference sign quirk
            m1, m2 = state[name]
            m1 += d1 * (g - m1)
            m2 += d2 * (g * g - m2)
            p -= lr_t * m1 / (m2.sqrt() + eps)


def test_whole_net_adam_trajectory_matches_torch():
    """Same whole-path check with the Adam updater: pins the one-minus
    decay convention, the wd sign quirk, bias correction from the
    0-based update count, and the ignored lr schedule — composed with
    conv+BN+pool+fc and the loss scaling."""
    conf = CONF.replace("updater = sgd", "updater = adam") \
        + "\neta = %g\n" % _adam_lr()
    rs = np.random.RandomState(1)
    protos = rs.randn(10, 1, 8, 8).astype(np.float32)

    def batch(i):
        r = np.random.RandomState(300 + i)
        y = r.randint(0, 10, BATCH)
        x = (protos[y] + r.randn(BATCH, 1, 8, 8) * 0.5).astype(np.float32)
        return x, y

    torch.manual_seed(11)
    model = TorchNet()
    model.train()
    state = {n: (torch.zeros_like(p), torch.zeros_like(p))
             for n, p in model.named_parameters()}

    net = Net(tokenize(conf))
    net.init_model()
    _export_weights(model, net)

    ours, theirs = [], []
    for i in range(30):
        x, y = batch(i)
        probs = net.extract_feature(
            DataBatch(x, y[:, None].astype(np.float32)),
            "top[-1]").reshape(BATCH, 10)
        ours.append(float(-np.mean(np.log(probs[np.arange(BATCH), y]
                                          + 1e-12))))
        net.update(DataBatch(x, y[:, None].astype(np.float32)))

        loss = torch.nn.functional.cross_entropy(
            model(torch.from_numpy(x)), torch.from_numpy(y).long())
        theirs.append(float(loss.detach()))
        model.zero_grad()
        loss.backward()
        _torch_adam_step(model, state, i)

    np.testing.assert_allclose(ours, theirs, rtol=5e-3, atol=5e-3)
    assert theirs[-1] < theirs[0] * 0.5, theirs


# ---------------------------------------------------------------------------
# Transformer whole-net trajectory (VERDICT r4 weak #4): the same 2-block
# causal GPT — dense and switch-MoE — trained 50 steps in the config DSL and
# in torch from identical weights; per-step losses and final weights must
# agree. Pins the attention scaling, pre-LN residual order, lm_softmax's
# shifted CE + loss scaling, the MoE top-1 routing + load-balance aux, and
# the SGD update — end to end, the sequence-model counterpart of the CNN
# trajectory above.
# ---------------------------------------------------------------------------

T_N, T_B, T_V, T_F, T_H = 16, 16, 32, 32, 2
T_STEPS = 50
T_ETA, T_MOM = 0.1, 0.9
MOE_E, MOE_AUXW = 4, 0.01


class _TorchBlock(torch.nn.Module):
    def __init__(self, moe: bool):
        super().__init__()
        F = T_F
        self.ln1 = torch.nn.LayerNorm(F)
        self.qkv = torch.nn.Linear(F, 3 * F)
        self.proj = torch.nn.Linear(F, F)
        self.ln2 = torch.nn.LayerNorm(F)
        self.moe = moe
        if moe:
            self.gate = torch.nn.Linear(F, MOE_E, bias=False)
            self.w_up = torch.nn.Parameter(torch.zeros(MOE_E, F, 4 * F))
            self.w_down = torch.nn.Parameter(torch.zeros(MOE_E, 4 * F, F))
        else:
            self.up = torch.nn.Linear(F, 4 * F)
            self.down = torch.nn.Linear(4 * F, F)

    def forward(self, h):
        b, n, f = h.shape
        x = self.ln1(h)
        q, k, v = self.qkv(x).split(f, dim=-1)
        d = f // T_H
        q = q.view(b, n, T_H, d).transpose(1, 2)
        k = k.view(b, n, T_H, d).transpose(1, 2)
        v = v.view(b, n, T_H, d).transpose(1, 2)
        s = (q @ k.transpose(-1, -2)) / d ** 0.5
        mask = torch.triu(torch.ones(n, n, dtype=torch.bool), 1)
        s = s.masked_fill(mask, float("-inf"))
        att = (torch.softmax(s, -1) @ v).transpose(1, 2).reshape(b, n, f)
        h = h + self.proj(att)
        x = self.ln2(h)
        aux = h.new_zeros(())
        if self.moe:
            # switch top-1 with ample capacity: every token served by its
            # argmax expert, scaled by the raw max probability (ops/moe.py)
            probs = torch.softmax(self.gate(x.reshape(-1, f)).float(), -1)
            top_p, top_i = probs.max(-1)
            xf = x.reshape(-1, f)
            out = torch.zeros_like(xf)
            for e in range(MOE_E):
                m = top_i == e
                if m.any():
                    ye = torch.relu(xf[m] @ self.w_up[e]) @ self.w_down[e]
                    out[m] = top_p[m, None].to(ye.dtype) * ye
            frac = torch.bincount(top_i, minlength=MOE_E).float() / xf.shape[0]
            aux = MOE_E * (frac * probs.mean(0)).sum()
            h = h + out.reshape(b, n, f)
        else:
            h = h + self.down(torch.relu(self.up(x)))
        return h, aux


class _TorchGPT(torch.nn.Module):
    def __init__(self, moe: bool):
        super().__init__()
        self.emb = torch.nn.Embedding(T_V, T_F)
        self.pos = torch.nn.Parameter(torch.zeros(T_N, T_F))
        self.blocks = torch.nn.ModuleList([_TorchBlock(moe)
                                           for _ in range(2)])
        self.lnf = torch.nn.LayerNorm(T_F)
        self.head = torch.nn.Linear(T_F, T_V, bias=False)

    def forward(self, ids):
        h = self.emb(ids) + self.pos[None]
        aux_total = h.new_zeros(())
        for blk in self.blocks:
            h, aux = blk(h)
            aux_total = aux_total + aux
        logits = self.head(self.lnf(h))
        ce = torch.nn.functional.cross_entropy(
            logits[:, :-1].reshape(-1, T_V).float(),
            ids[:, 1:].reshape(-1))
        return ce + MOE_AUXW * aux_total


def _export_gpt_weights(model, net, moe: bool):
    """torch -> config-DSL net. Torch Linear weight (out,in) IS the DSL
    qkv/proj convention (x @ W.T); 1x1 convs are HWIO so MLP weights
    transpose; MoE expert tensors map 1:1."""
    sd = {k: v.detach().numpy() for k, v in model.state_dict().items()}
    net.set_weight("emb", "wmat", sd["emb.weight"])
    net.set_weight("emb", "pos", sd["pos"])
    for i in range(2):
        p = "blocks.%d." % i
        net.set_weight("ln%da" % i, "wmat", sd[p + "ln1.weight"])
        net.set_weight("ln%da" % i, "bias", sd[p + "ln1.bias"])
        net.set_weight("att%d" % i, "qkv", sd[p + "qkv.weight"])
        net.set_weight("att%d" % i, "qkv_bias", sd[p + "qkv.bias"])
        net.set_weight("att%d" % i, "proj", sd[p + "proj.weight"])
        net.set_weight("att%d" % i, "proj_bias", sd[p + "proj.bias"])
        net.set_weight("ln%db" % i, "wmat", sd[p + "ln2.weight"])
        net.set_weight("ln%db" % i, "bias", sd[p + "ln2.bias"])
        if moe:
            net.set_weight("moe%d" % i, "gate", sd[p + "gate.weight"].T)
            net.set_weight("moe%d" % i, "w_up", sd[p + "w_up"])
            net.set_weight("moe%d" % i, "w_down", sd[p + "w_down"])
        else:
            net.set_weight("mlp%da" % i, "wmat",
                           sd[p + "up.weight"].T[None, None])
            net.set_weight("mlp%da" % i, "bias", sd[p + "up.bias"])
            net.set_weight("mlp%db" % i, "wmat",
                           sd[p + "down.weight"].T[None, None])
            net.set_weight("mlp%db" % i, "bias", sd[p + "down.bias"])
    net.set_weight("lnf", "wmat", sd["lnf.weight"])
    net.set_weight("lnf", "bias", sd["lnf.bias"])
    net.set_weight("head", "wmat", sd["head.weight"].T[None, None])


def _run_gpt_trajectory(moe: bool):
    from cxxnet_tpu.models import gpt_lm_config

    cfg = gpt_lm_config(seq_len=T_N, vocab_size=T_V, feat=T_F, nhead=T_H,
                        nblock=2, batch_size=T_B, dev="cpu:0", eta=T_ETA,
                        momentum=T_MOM,
                        moe_experts=MOE_E if moe else 0)
    if moe:
        # ample capacity: no drops, so the torch oracle's dense routing
        # is exact; fix dispatch so the trajectory is deterministic
        cfg = cfg.replace("  nexpert = %d" % MOE_E,
                          "  nexpert = %d\n  capacity_factor = 64" % MOE_E)
    cfg += "\nwd = 0\n"
    net = Net(tokenize(cfg))
    net.init_model()

    torch.manual_seed(11)
    model = _TorchGPT(moe)
    with torch.no_grad():
        for p in model.parameters():
            p.normal_(0, 0.05)
    model.train()
    _export_gpt_weights(model, net, moe)
    bufs = {n: torch.zeros_like(p) for n, p in model.named_parameters()}

    ours, theirs = [], []
    for i in range(T_STEPS):
        # learnable corpus (the trajectory check is meaningless on a flat
        # loss): cyclic successor sequences with 10% corruption
        r = np.random.RandomState(500 + i)
        start = r.randint(0, T_V, (T_B, 1))
        ids = (start + np.arange(T_N)) % T_V
        noise = r.randint(0, T_V, ids.shape)
        ids = np.where(r.rand(*ids.shape) < 0.1, noise, ids)
        ids = ids.astype(np.float32)
        net.update(DataBatch(ids.reshape(T_B, 1, 1, T_N), ids))
        ours.append(net.last_loss())

        loss = model(torch.from_numpy(ids.astype(np.int64)))
        theirs.append(float(loss.detach()))
        model.zero_grad()
        loss.backward()
        with torch.no_grad():
            for name, p in model.named_parameters():
                bufs[name] = T_MOM * bufs[name] - T_ETA * p.grad
                p += bufs[name]
    return np.asarray(ours), np.asarray(theirs), net, model


@pytest.mark.parametrize("moe", [False, True], ids=["dense", "moe"])
def test_gpt_whole_net_trajectory_matches_torch(moe):
    ours, theirs, net, model = _run_gpt_trajectory(moe)
    np.testing.assert_allclose(ours, theirs, rtol=5e-3, atol=5e-3)
    assert theirs[-1] < theirs[0] - 0.1, theirs
    # final weights agree too (drift compounds over 50 steps, so any
    # semantic mismatch in grads/updates would blow through this)
    sd = {k: v.detach().numpy() for k, v in model.state_dict().items()}
    np.testing.assert_allclose(net.get_weight("emb", "wmat"),
                               sd["emb.weight"], rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(net.get_weight("att1", "qkv"),
                               sd["blocks.1.qkv.weight"],
                               rtol=2e-3, atol=2e-3)
    if moe:
        np.testing.assert_allclose(net.get_weight("moe0", "w_up"),
                                   sd["blocks.0.w_up"],
                                   rtol=2e-3, atol=2e-3)
    else:
        np.testing.assert_allclose(
            net.get_weight("mlp1b", "wmat")[0, 0],
            sd["blocks.1.down.weight"].T, rtol=2e-3, atol=2e-3)
