"""Optimizer math + schedule tests (reference: src/updater/)."""

import jax.numpy as jnp
import numpy as np

from cxxnet_tpu.updaters import (AdamUpdater, NAGUpdater, SGDUpdater,
                                 UpdaterParam, clip_grad, create_updater)


def test_sgd_momentum_math():
    upd = SGDUpdater("wmat", [("eta", "0.1"), ("momentum", "0.9"),
                              ("wd", "0.01")])
    w = jnp.asarray(np.ones((3,), np.float32))
    g = jnp.asarray(np.full((3,), 2.0, np.float32))
    s = upd.init_state(w)
    w1, s1 = upd.update(w, g, s, 0)
    # m = 0.9*0 - 0.1*(2 + 0.01*1) = -0.201 ; w = 1 - 0.201
    np.testing.assert_allclose(np.asarray(w1), 1 - 0.201, rtol=1e-6)
    w2, s2 = upd.update(w1, g, s1, 1)
    m2 = 0.9 * -0.201 - 0.1 * (2 + 0.01 * float(w1[0]))
    np.testing.assert_allclose(np.asarray(w2), float(w1[0]) + m2, rtol=1e-6)


def test_sgd_nan_grad_clipped_to_zero():
    upd = SGDUpdater("wmat", [("eta", "0.1"), ("momentum", "0.0"),
                              ("clip_gradient", "1.0")])
    w = jnp.zeros((3,))
    g = jnp.asarray(np.array([np.nan, 5.0, -5.0], np.float32))
    w1, _ = upd.update(w, g, upd.init_state(w), 0)
    np.testing.assert_allclose(np.asarray(w1), [0.0, -0.1, 0.1], rtol=1e-6)


def test_nag_math():
    upd = NAGUpdater("wmat", [("eta", "0.1"), ("momentum", "0.9")])
    w = jnp.ones((2,))
    g = jnp.full((2,), 1.0)
    s = upd.init_state(w)
    w1, s1 = upd.update(w, g, s, 0)
    # m_new = -0.1; w += 1.9*(-0.1) - 0.9*0 = -0.19
    np.testing.assert_allclose(np.asarray(w1), 1 - 0.19, rtol=1e-6)


def test_adam_math():
    upd = AdamUpdater("wmat", [("eta", "0.001")])
    w = jnp.ones((2,))
    g = jnp.full((2,), 3.0)
    s = upd.init_state(w)
    w1, s1 = upd.update(w, g, s, 0)
    fix1 = 1 - 0.9 ** 1
    fix2 = 1 - 0.999 ** 1
    lr_t = 0.001 * np.sqrt(fix2) / fix1
    m1 = 0.1 * 3.0
    m2 = 0.001 * 9.0
    expect = 1 - lr_t * (m1 / (np.sqrt(m2) + 1e-8))
    np.testing.assert_allclose(np.asarray(w1), expect, rtol=1e-5)


def test_lr_schedules():
    p = UpdaterParam("wmat")
    p.set_param("eta", "0.5")
    p.set_param("lr:schedule", "expdecay")
    p.set_param("lr:gamma", "0.5")
    p.set_param("lr:step", "10")
    lr, _ = p.schedule(10)
    np.testing.assert_allclose(float(lr), 0.25, rtol=1e-5)
    lr, _ = p.schedule(5)
    np.testing.assert_allclose(float(lr), 0.5 * 0.5 ** 0.5, rtol=1e-5)

    p2 = UpdaterParam("wmat")
    p2.set_param("eta", "0.5")
    p2.set_param("lr:schedule", "factor")
    p2.set_param("lr:factor", "0.1")
    p2.set_param("lr:step", "10")
    # integer division: epochs 0-9 -> 0.5, 10-19 -> 0.05
    np.testing.assert_allclose(float(p2.schedule(9)[0]), 0.5, rtol=1e-5)
    np.testing.assert_allclose(float(p2.schedule(10)[0]), 0.05, rtol=1e-5)

    p3 = UpdaterParam("wmat")
    p3.set_param("eta", "0.5")
    p3.set_param("lr:schedule", "factor")
    p3.set_param("lr:factor", "1e-9")
    p3.set_param("lr:step", "1")
    # lr_minimum floor (default 1e-5)
    np.testing.assert_allclose(float(p3.schedule(5)[0]), 1e-5, rtol=1e-5)


def test_tag_scoped_params():
    upd_w = SGDUpdater("wmat", [("wd", "0.01"), ("bias:wd", "0.0")])
    upd_b = SGDUpdater("bias", [("wd", "0.01"), ("bias:wd", "0.0")])
    assert upd_w.param.wd == 0.01
    assert upd_b.param.wd == 0.0


def test_factory():
    assert isinstance(create_updater("sgd", "wmat", []), SGDUpdater)
    assert isinstance(create_updater("nag", "wmat", []), NAGUpdater)
    assert isinstance(create_updater("adam", "wmat", []), AdamUpdater)


def test_clip_grad():
    g = jnp.asarray(np.array([np.nan, 10.0, -10.0, 0.5], np.float32))
    out = np.asarray(clip_grad(g, 2.0))
    np.testing.assert_allclose(out, [0.0, 2.0, -2.0, 0.5])


def test_adamw_decoupled_decay():
    """AdamW: wd shrinks weights by lr*wd directly; the moment estimates see
    the raw gradient (unlike adam, whose wd enters the gradient)."""
    from cxxnet_tpu.updaters import AdamWUpdater
    cfg = [("eta", "0.1"), ("wd", "0.5"), ("beta1", "0.1"), ("beta2", "0.001")]
    upd_w = AdamWUpdater("wmat", cfg)
    upd_a = AdamUpdater("wmat", cfg)
    w = jnp.asarray(np.full((3,), 2.0, np.float32))
    g = jnp.asarray(np.full((3,), 1.0, np.float32))
    w1, s1 = upd_w.update(w, g, upd_w.init_state(w), 0)
    # moments identical to wd=0 adam; decay term = lr*wd*w on top
    upd_0 = AdamUpdater("wmat", [("eta", "0.1"), ("wd", "0"),
                                 ("beta1", "0.1"), ("beta2", "0.001")])
    w_ref, s_ref = upd_0.update(w, g, upd_0.init_state(w), 0)
    np.testing.assert_allclose(np.asarray(w1),
                               np.asarray(w_ref) - 0.1 * 0.5 * np.asarray(w),
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(s1["m1"]), np.asarray(s_ref["m1"]))
    # and differs from the reference adam's coupled wd
    w_a, _ = upd_a.update(w, g, upd_a.init_state(w), 0)
    assert not np.allclose(np.asarray(w1), np.asarray(w_a))


def test_adamw_matches_torch():
    """Cross-framework oracle: one AdamW step vs torch.optim.AdamW (betas
    converted from the one-minus convention)."""
    import pytest
    torch = pytest.importorskip("torch")
    from cxxnet_tpu.updaters import AdamWUpdater

    lr, wd, d1, d2 = 0.05, 0.2, 0.1, 0.001
    w0 = np.array([1.5, -2.0, 0.5], np.float32)
    g0 = np.array([0.3, -0.7, 1.1], np.float32)

    upd = AdamWUpdater("wmat", [("eta", str(lr)), ("wd", str(wd)),
                                ("beta1", str(d1)), ("beta2", str(d2))])
    w1, _ = upd.update(jnp.asarray(w0), jnp.asarray(g0),
                       upd.init_state(jnp.asarray(w0)), 0)

    tw = torch.tensor(w0, requires_grad=True)
    opt = torch.optim.AdamW([tw], lr=lr, betas=(1 - d1, 1 - d2),
                            weight_decay=wd, eps=1e-8)
    tw.grad = torch.tensor(g0)
    opt.step()
    np.testing.assert_allclose(np.asarray(w1), tw.detach().numpy(),
                               rtol=2e-5, atol=2e-6)


def test_global_norm_scale():
    from cxxnet_tpu.updaters import global_norm_scale
    grads = {"a": {"w": jnp.asarray(np.array([3.0, 0.0], np.float32))},
             "b": {"w": jnp.asarray(np.array([0.0, 4.0], np.float32))}}
    # ||g|| = 5; clip to 2.5 -> scale 0.5
    np.testing.assert_allclose(float(global_norm_scale(grads, 2.5)), 0.5,
                               rtol=1e-6)
    # under the bound -> no scaling
    np.testing.assert_allclose(float(global_norm_scale(grads, 10.0)), 1.0)
    # NaN leaves are excluded, not poisoning the norm
    grads["a"]["w"] = jnp.asarray(np.array([np.nan, 3.0], np.float32))
    assert np.isfinite(float(global_norm_scale(grads, 2.5)))
