"""Optimizer math + schedule tests (reference: src/updater/)."""

import jax.numpy as jnp
import numpy as np

from cxxnet_tpu.updaters import (AdamUpdater, NAGUpdater, SGDUpdater,
                                 UpdaterParam, clip_grad, create_updater)


def test_sgd_momentum_math():
    upd = SGDUpdater("wmat", [("eta", "0.1"), ("momentum", "0.9"),
                              ("wd", "0.01")])
    w = jnp.asarray(np.ones((3,), np.float32))
    g = jnp.asarray(np.full((3,), 2.0, np.float32))
    s = upd.init_state(w)
    w1, s1 = upd.update(w, g, s, 0)
    # m = 0.9*0 - 0.1*(2 + 0.01*1) = -0.201 ; w = 1 - 0.201
    np.testing.assert_allclose(np.asarray(w1), 1 - 0.201, rtol=1e-6)
    w2, s2 = upd.update(w1, g, s1, 1)
    m2 = 0.9 * -0.201 - 0.1 * (2 + 0.01 * float(w1[0]))
    np.testing.assert_allclose(np.asarray(w2), float(w1[0]) + m2, rtol=1e-6)


def test_sgd_nan_grad_clipped_to_zero():
    upd = SGDUpdater("wmat", [("eta", "0.1"), ("momentum", "0.0"),
                              ("clip_gradient", "1.0")])
    w = jnp.zeros((3,))
    g = jnp.asarray(np.array([np.nan, 5.0, -5.0], np.float32))
    w1, _ = upd.update(w, g, upd.init_state(w), 0)
    np.testing.assert_allclose(np.asarray(w1), [0.0, -0.1, 0.1], rtol=1e-6)


def test_nag_math():
    upd = NAGUpdater("wmat", [("eta", "0.1"), ("momentum", "0.9")])
    w = jnp.ones((2,))
    g = jnp.full((2,), 1.0)
    s = upd.init_state(w)
    w1, s1 = upd.update(w, g, s, 0)
    # m_new = -0.1; w += 1.9*(-0.1) - 0.9*0 = -0.19
    np.testing.assert_allclose(np.asarray(w1), 1 - 0.19, rtol=1e-6)


def test_adam_math():
    upd = AdamUpdater("wmat", [("eta", "0.001")])
    w = jnp.ones((2,))
    g = jnp.full((2,), 3.0)
    s = upd.init_state(w)
    w1, s1 = upd.update(w, g, s, 0)
    fix1 = 1 - 0.9 ** 1
    fix2 = 1 - 0.999 ** 1
    lr_t = 0.001 * np.sqrt(fix2) / fix1
    m1 = 0.1 * 3.0
    m2 = 0.001 * 9.0
    expect = 1 - lr_t * (m1 / (np.sqrt(m2) + 1e-8))
    np.testing.assert_allclose(np.asarray(w1), expect, rtol=1e-5)


def test_lr_schedules():
    p = UpdaterParam("wmat")
    p.set_param("eta", "0.5")
    p.set_param("lr:schedule", "expdecay")
    p.set_param("lr:gamma", "0.5")
    p.set_param("lr:step", "10")
    lr, _ = p.schedule(10)
    np.testing.assert_allclose(float(lr), 0.25, rtol=1e-5)
    lr, _ = p.schedule(5)
    np.testing.assert_allclose(float(lr), 0.5 * 0.5 ** 0.5, rtol=1e-5)

    p2 = UpdaterParam("wmat")
    p2.set_param("eta", "0.5")
    p2.set_param("lr:schedule", "factor")
    p2.set_param("lr:factor", "0.1")
    p2.set_param("lr:step", "10")
    # integer division: epochs 0-9 -> 0.5, 10-19 -> 0.05
    np.testing.assert_allclose(float(p2.schedule(9)[0]), 0.5, rtol=1e-5)
    np.testing.assert_allclose(float(p2.schedule(10)[0]), 0.05, rtol=1e-5)

    p3 = UpdaterParam("wmat")
    p3.set_param("eta", "0.5")
    p3.set_param("lr:schedule", "factor")
    p3.set_param("lr:factor", "1e-9")
    p3.set_param("lr:step", "1")
    # lr_minimum floor (default 1e-5)
    np.testing.assert_allclose(float(p3.schedule(5)[0]), 1e-5, rtol=1e-5)


def test_tag_scoped_params():
    upd_w = SGDUpdater("wmat", [("wd", "0.01"), ("bias:wd", "0.0")])
    upd_b = SGDUpdater("bias", [("wd", "0.01"), ("bias:wd", "0.0")])
    assert upd_w.param.wd == 0.01
    assert upd_b.param.wd == 0.0


def test_factory():
    assert isinstance(create_updater("sgd", "wmat", []), SGDUpdater)
    assert isinstance(create_updater("nag", "wmat", []), NAGUpdater)
    assert isinstance(create_updater("adam", "wmat", []), AdamUpdater)


def test_clip_grad():
    g = jnp.asarray(np.array([np.nan, 10.0, -10.0, 0.5], np.float32))
    out = np.asarray(clip_grad(g, 2.0))
    np.testing.assert_allclose(out, [0.0, 2.0, -2.0, 0.5])
