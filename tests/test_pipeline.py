"""GPipe pipeline parallelism vs sequential execution (differential test)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cxxnet_tpu.parallel.mesh import make_mesh
from cxxnet_tpu.parallel.pipeline import gpipe

FEAT = 16
NBLOCK = 8


def block_fn(p, h):
    h2 = jnp.tanh(h @ p["w"] + p["b"])
    return h + h2          # residual keeps magnitudes stable through 8 blocks


def stacked_params(rs):
    return {
        "w": jnp.asarray(rs.randn(NBLOCK, FEAT, FEAT).astype(np.float32) * 0.3),
        "b": jnp.asarray(rs.randn(NBLOCK, FEAT).astype(np.float32) * 0.1),
    }


def sequential(params, x):
    return jax.lax.scan(lambda h, p: (block_fn(p, h), None), x, params)[0]


@pytest.mark.parametrize("pipe,micro", [(1, 2), (2, 4), (4, 4), (8, 8)])
def test_gpipe_matches_sequential(pipe, micro):
    rs = np.random.RandomState(0)
    params = stacked_params(rs)
    x = jnp.asarray(rs.randn(16, FEAT).astype(np.float32))
    mesh = make_mesh("cpu:0-7", pipeline_parallel=pipe)
    ref = sequential(params, x)
    out = jax.jit(lambda p, xx: gpipe(block_fn, p, xx, mesh, micro))(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_gpipe_gradients_match_sequential():
    rs = np.random.RandomState(1)
    params = stacked_params(rs)
    x = jnp.asarray(rs.randn(8, FEAT).astype(np.float32))
    mesh = make_mesh("cpu:0-7", pipeline_parallel=4)

    g_ref = jax.grad(lambda p: (sequential(p, x) ** 2).sum())(params)
    g_out = jax.jit(jax.grad(
        lambda p: (gpipe(block_fn, p, x, mesh, 4) ** 2).sum()))(params)
    for a, b in zip(jax.tree.leaves(g_out), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_gpipe_composes_with_data_parallel():
    rs = np.random.RandomState(2)
    params = stacked_params(rs)
    x = jnp.asarray(rs.randn(8, FEAT).astype(np.float32))
    mesh = make_mesh("cpu:0-7", pipeline_parallel=4)   # data=2 x pipe=4
    assert mesh.shape["data"] == 2
    ref = sequential(params, x)
    out = jax.jit(lambda p, xx: gpipe(block_fn, p, xx, mesh, 2))(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_gpipe_rejects_bad_partition():
    rs = np.random.RandomState(3)
    params = stacked_params(rs)
    x = jnp.asarray(rs.randn(8, FEAT).astype(np.float32))
    mesh = make_mesh("cpu:0-7", pipeline_parallel=4)
    with pytest.raises(ValueError, match="n_microbatch"):
        gpipe(block_fn, params, x, mesh, 3)
    mesh8 = make_mesh("cpu:0-7", pipeline_parallel=8)
    bad = {"w": params["w"][:6], "b": params["b"][:6]}
    with pytest.raises(ValueError, match="not divisible"):
        gpipe(block_fn, bad, x, mesh8, 4)


# ---------------------------------------------------------------- config DSL
# pipeline_parallel = k on a netconfig transformer (round 4): the Net
# detects the repeated block stack and runs it through gpipe. Equivalence
# vs pure data parallelism is the correctness bar (same bar as the gpt.py
# dryrun matrix).

from cxxnet_tpu import Net  # noqa: E402
from cxxnet_tpu.io.data import DataBatch  # noqa: E402
from cxxnet_tpu.models import transformer_config  # noqa: E402
from cxxnet_tpu.utils.config import ConfigError, tokenize  # noqa: E402


def _tbatch(seed, n=16, seq=32):
    rs = np.random.RandomState(seed)
    x = rs.randint(0, 256, (n, 1, 1, seq)).astype(np.float32)
    y = rs.randint(0, 10, (n, 1)).astype(np.float32)
    return DataBatch(x, y)


def _tnet(pp, nblock=4, micro=0, **kw):
    cfg = transformer_config(seq_len=32, feat=32, nhead=4, nblock=nblock,
                             batch_size=16, dev="cpu",
                             pipeline_parallel=pp,
                             pipeline_microbatch=micro, **kw)
    net = Net(tokenize(cfg))
    net.init_model()
    return net


def test_dsl_pp_detects_transformer_blocks():
    net = _tnet(pp=2)
    seg = net._pp_segment
    assert seg is not None
    assert seg.count == 4 and seg.period == 10


def test_dsl_pp_matches_dp():
    """pp2 x dp4 training trajectory == dp8 (same seed, same batches)."""
    nets = [_tnet(pp=1), _tnet(pp=2), _tnet(pp=2, micro=4)]
    for step in range(4):
        b = _tbatch(step)
        for net in nets:
            net.update(b)
    ref = nets[0].params
    for net in nets[1:]:
        for k in ref:
            for tag in ref[k]:
                d = float(jnp.max(jnp.abs(
                    ref[k][tag] - net.params[k][tag])))
                assert d < 1e-5, (k, tag, d)


class _OneBatchIter:
    def __init__(self, batch):
        self.batch, self._served = batch, False

    def before_first(self):
        self._served = False

    def next(self):
        if self._served:
            return False
        self._served = True
        return True

    def value(self):
        return self.batch


def test_dsl_pp_eval_forward():
    """The evaluate/predict forward also routes through the pipeline."""
    n1, n2 = _tnet(pp=1), _tnet(pp=2)
    b = _tbatch(100)
    e1 = n1.evaluate(_OneBatchIter(b), "t")
    e2 = n2.evaluate(_OneBatchIter(b), "t")
    assert e1 == e2


def test_dsl_pp_rejections():
    # repetition count not divisible by the pipe axis
    with pytest.raises(ConfigError, match="divide the repeated block"):
        _tnet(pp=8, nblock=4)       # 8 stages > 4 blocks
    # no repeated segment: single-block net
    with pytest.raises(ConfigError, match="no repeated block segment"):
        _tnet(pp=2, nblock=1)
    # moe blocks emit an aux loss that gpipe's inner context would drop;
    # they are excluded from config-path pipelining (gpt.py path instead)
    with pytest.raises(ConfigError, match="no repeated block segment"):
        _tnet(pp=2, moe_experts=4)
    # composition boundary: sp/ep inside a pipelined segment is the
    # models/gpt.py path, the config path rejects it at build (tp
    # composes since round 5 — test_dsl_pp_tp_composition_matches_dp)
    with pytest.raises(ConfigError, match="seq/expert"):
        _tnet(pp=2, seq_parallel=2)
    # microbatch must divide the per-shard batch (16/dp4 = 4)
    with pytest.raises(ConfigError, match="pipeline_microbatch"):
        _tnet(pp=2, micro=3)


def test_dsl_pp_internal_node_guard():
    """Nodes inside the pipelined segment are never materialized; binding a
    metric or extract to one must fail at build/call time, not in jit."""
    net = _tnet(pp=2)
    with pytest.raises(ConfigError, match="internal to the block segment"):
        list(net.forward_iter(_OneBatchIter(_tbatch(0)), node="b0a"))
    # a metric bound to an internal node fails at init_model
    cfg = transformer_config(seq_len=32, feat=32, nhead=4, nblock=4,
                             batch_size=16, dev="cpu", pipeline_parallel=2)
    cfg += "\nmetric[label,b1b] = error\n"
    net2 = Net(tokenize(cfg))
    with pytest.raises(ConfigError, match="internal to the block segment"):
        net2.init_model()


def test_dsl_pp_through_cli(tmp_path, capfd):
    """pipeline_parallel from an on-disk config through the CLI task — the
    outermost user surface (config file -> LearnTask -> pipelined net)."""
    import os
    from cxxnet_tpu.cli import LearnTask

    rs = np.random.RandomState(0)
    ids = rs.randint(0, 32, (64, 32)).astype(np.uint8)
    labels = rs.randint(0, 10, 64)
    # idx-format files for the mnist iterator (1x32 'images' = token ids)
    import gzip
    import struct
    with gzip.open(tmp_path / "img.gz", "wb") as f:
        f.write(struct.pack(">iiii", 2051, 64, 1, 32))
        f.write(ids.tobytes())
    with gzip.open(tmp_path / "lab.gz", "wb") as f:
        f.write(struct.pack(">ii", 2049, 64))
        f.write(labels.astype(np.uint8).tobytes())

    cfg = transformer_config(seq_len=32, vocab_size=32, feat=32, nhead=4,
                             nblock=2, num_classes=10, batch_size=16,
                             dev="cpu", pipeline_parallel=2)
    conf = tmp_path / "pp.conf"
    conf.write_text("""
data = train
iter = mnist
    path_img = "%s"
    path_label = "%s"
iter = end
%s
num_round = 2
max_round = 2
save_model = 0
""" % (tmp_path / "img.gz", tmp_path / "lab.gz", cfg))
    assert LearnTask().run([str(conf)]) == 0
    err = capfd.readouterr().err
    assert "[1]" in err and "train-error:" in err


def test_dsl_pp_tp_composition_matches_dp():
    """Round 5 (VERDICT r4 #3): model_parallel inside the pipelined
    segment through the config DSL — megatron attention (per-head qkv
    sharding, permuted at stack time, one psum) + column-parallel 1x1
    convs + replicated fallback — matches dp8 to 1e-5 over a 3-step
    trajectory, with and without remat."""
    from cxxnet_tpu.models import gpt_lm_config
    from cxxnet_tpu.nnet.pipeline_dsl import _pp_tp_plan

    rs = np.random.RandomState(0)
    N, B, V = 16, 16, 32
    ids = rs.randint(0, V, (B, N)).astype(np.float32)
    data = ids.reshape(B, 1, 1, N)

    def run(**kw):
        cfg = gpt_lm_config(seq_len=N, vocab_size=V, feat=16, nhead=4,
                            nblock=2, batch_size=B, dev="cpu:0-7", **kw)
        net = Net(tokenize(cfg))
        net.init_model()
        for _ in range(3):
            net.update(DataBatch(data, ids))
        return net

    base = run()
    for label, kw in [("pp2xtp2", dict(pipeline_parallel=2,
                                       model_parallel=2)),
                      ("pp2xtp2_remat", dict(pipeline_parallel=2,
                                             model_parallel=2, remat=1))]:
        net = run(**kw)
        # the plans must actually engage tensor parallelism (not the
        # replicated fallback) for the attention + both MLP convs
        plans, specs = _pp_tp_plan(net, net._pp_segment, 2)
        assert sorted(plans.values()) == \
            ["attn", "conv_col", "conv_col", "plain", "plain"], plans
        assert any(s == "model" for s in specs["2"]["qkv"]), specs["2"]
        assert abs(net.last_loss() - base.last_loss()) < 1e-4, label
        dmax = max(float(np.max(np.abs(np.asarray(net.params[k][t])
                                       - np.asarray(base.params[k][t]))))
                   for k in base.params for t in base.params[k])
        assert dmax < 1e-5, (label, dmax)


def test_dsl_pp_tp_no_bias():
    """no_bias attention/conv layers inside a tp-sharded pipelined
    segment: the spec pytree must mirror the tags actually present
    (review r5 finding)."""
    from cxxnet_tpu.models import gpt_lm_config

    rs = np.random.RandomState(0)
    N, B, V = 16, 16, 32
    ids = rs.randint(0, V, (B, N)).astype(np.float32)
    cfg = gpt_lm_config(seq_len=N, vocab_size=V, feat=16, nhead=4,
                        nblock=2, batch_size=B, dev="cpu:0-7",
                        pipeline_parallel=2, model_parallel=2)
    cfg += "\nno_bias = 1\n"
    net = Net(tokenize(cfg))
    net.init_model()
    net.update(DataBatch(ids.reshape(B, 1, 1, N), ids))
    assert np.isfinite(net.last_loss())
