"""GPipe pipeline parallelism vs sequential execution (differential test)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cxxnet_tpu.parallel.mesh import make_mesh
from cxxnet_tpu.parallel.pipeline import gpipe

FEAT = 16
NBLOCK = 8


def block_fn(p, h):
    h2 = jnp.tanh(h @ p["w"] + p["b"])
    return h + h2          # residual keeps magnitudes stable through 8 blocks


def stacked_params(rs):
    return {
        "w": jnp.asarray(rs.randn(NBLOCK, FEAT, FEAT).astype(np.float32) * 0.3),
        "b": jnp.asarray(rs.randn(NBLOCK, FEAT).astype(np.float32) * 0.1),
    }


def sequential(params, x):
    return jax.lax.scan(lambda h, p: (block_fn(p, h), None), x, params)[0]


@pytest.mark.parametrize("pipe,micro", [(1, 2), (2, 4), (4, 4), (8, 8)])
def test_gpipe_matches_sequential(pipe, micro):
    rs = np.random.RandomState(0)
    params = stacked_params(rs)
    x = jnp.asarray(rs.randn(16, FEAT).astype(np.float32))
    mesh = make_mesh("cpu:0-7", pipeline_parallel=pipe)
    ref = sequential(params, x)
    out = jax.jit(lambda p, xx: gpipe(block_fn, p, xx, mesh, micro))(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_gpipe_gradients_match_sequential():
    rs = np.random.RandomState(1)
    params = stacked_params(rs)
    x = jnp.asarray(rs.randn(8, FEAT).astype(np.float32))
    mesh = make_mesh("cpu:0-7", pipeline_parallel=4)

    g_ref = jax.grad(lambda p: (sequential(p, x) ** 2).sum())(params)
    g_out = jax.jit(jax.grad(
        lambda p: (gpipe(block_fn, p, x, mesh, 4) ** 2).sum()))(params)
    for a, b in zip(jax.tree.leaves(g_out), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_gpipe_composes_with_data_parallel():
    rs = np.random.RandomState(2)
    params = stacked_params(rs)
    x = jnp.asarray(rs.randn(8, FEAT).astype(np.float32))
    mesh = make_mesh("cpu:0-7", pipeline_parallel=4)   # data=2 x pipe=4
    assert mesh.shape["data"] == 2
    ref = sequential(params, x)
    out = jax.jit(lambda p, xx: gpipe(block_fn, p, xx, mesh, 2))(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_gpipe_rejects_bad_partition():
    rs = np.random.RandomState(3)
    params = stacked_params(rs)
    x = jnp.asarray(rs.randn(8, FEAT).astype(np.float32))
    mesh = make_mesh("cpu:0-7", pipeline_parallel=4)
    with pytest.raises(ValueError, match="n_microbatch"):
        gpipe(block_fn, params, x, mesh, 3)
    mesh8 = make_mesh("cpu:0-7", pipeline_parallel=8)
    bad = {"w": params["w"][:6], "b": params["b"][:6]}
    with pytest.raises(ValueError, match="not divisible"):
        gpipe(block_fn, bad, x, mesh8, 4)
