"""Chunked prefill + shared-prefix KV reuse (serve/engine.py chunk step,
serve/prefix_cache.py trie, scheduler interleaving). The load-bearing
invariants: (1) a request prefilled in fixed-size chunks — at any prompt
length, including non-multiples of the chunk — produces tokens identical
to its solo ``gpt_decode`` run; (2) a prefix-cache hit restores K/V
bit-identical to recomputing it, so hit and cold paths emit the same
tokens; (3) compiled prefill programs are bounded by chunk buckets, not
distinct prompt lengths (the extended RecompileGuard pins it); (4) a
long prompt's prefill cannot stall an active row's decode — chunks and
ticks interleave."""

import time

import jax
import numpy as np
import pytest

from cxxnet_tpu.analysis.findings import LintError
from cxxnet_tpu.models.gpt import GPTConfig, gpt_decode, gpt_init
from cxxnet_tpu.serve import DecodeEngine, InferenceServer, PrefixCache

CFG = GPTConfig(vocab_size=32, seq_len=48, n_layer=2, n_head=2, feat=16,
                n_microbatch=1)
PARAMS = gpt_init(jax.random.PRNGKey(5), CFG)


def _prompt(rs, n):
    return rs.randint(0, CFG.vocab_size, (n,)).astype(np.int32)


def _ref(prompt, max_new, **kw):
    """The offline oracle: the same request run alone through
    gpt_decode."""
    seed = kw.pop("seed", 0)
    t = kw.get("temperature", 0.0)
    rng = jax.random.PRNGKey(seed) if t > 0 else None
    return np.asarray(gpt_decode(PARAMS, prompt[None], max_new, CFG,
                                 rng=rng, **kw))[0]


# ------------------------------------------------------ token identity
def test_chunked_prefill_matches_offline_path():
    """The tentpole invariant: prompts whose lengths are NOT chunk
    multiples (plus exact multiples and shorter-than-one-chunk), with
    mixed sampling params, all reproduce their solo gpt_decode run when
    prefilled 4 tokens at a time."""
    rs = np.random.RandomState(0)
    cases = [
        dict(n=3, max_tokens=5),                        # < one chunk
        dict(n=4, max_tokens=6),                        # exact multiple
        dict(n=5, max_tokens=4, temperature=1.0, seed=3),
        dict(n=9, max_tokens=6, temperature=0.8, top_k=5, top_p=0.9,
             seed=7),
        dict(n=13, max_tokens=5),                       # 3 chunks + 1
        dict(n=8, max_tokens=4, temperature=1.2, top_k=3, seed=11),
    ]
    with InferenceServer(CFG, PARAMS, slots=2, queue=16,
                         prefill_chunk=4) as srv:
        handles = []
        for c in cases:
            c = dict(c)
            c["prompt"] = _prompt(rs, c.pop("n"))
            handles.append((c, srv.submit(c["prompt"],
                                          **{k: v for k, v in c.items()
                                             if k != "prompt"})))
        for c, h in handles:
            res = srv.result(h, timeout=300)
            assert res.status == "ok", (res.status, res.error)
            kw = {k: v for k, v in c.items() if k not in ("prompt",
                                                          "max_tokens")}
            np.testing.assert_array_equal(
                res.tokens, _ref(c["prompt"], c["max_tokens"], **kw))
        m = srv.metrics()
    assert m["prefill_chunks_per_req"] >= 1.0
    assert set(m["prefill_chunk_ms"]) == {"p50", "p95", "p99"}


def test_recycled_slot_multichunk_prompts_no_prefix_reuse():
    """Chunked prefill does NOT rewrite the whole row — a recycled
    slot's stale tail must still be unreachable. One slot, two
    multi-chunk prompts back to back, prefix cache OFF so nothing is
    shared: both must match their solo runs."""
    rs = np.random.RandomState(1)
    a, b = _prompt(rs, 11), _prompt(rs, 7)
    with InferenceServer(CFG, PARAMS, slots=1, queue=8, prefill_chunk=4,
                         prefix_mb=0.0) as srv:
        ha = srv.submit(a, max_tokens=8, temperature=0.7, seed=2)
        hb = srv.submit(b, max_tokens=8, temperature=0.7, seed=9)
        res_a = srv.result(ha, timeout=300)
        res_b = srv.result(hb, timeout=300)
        assert hb.slot == ha.slot == 0
    np.testing.assert_array_equal(
        res_a.tokens, _ref(a, 8, temperature=0.7, seed=2))
    np.testing.assert_array_equal(
        res_b.tokens, _ref(b, 8, temperature=0.7, seed=9))


# ------------------------------------------------------- prefix cache
def test_prefix_hit_matches_cold_path():
    """A second request sharing a 12-token prefix restores 3 cached
    chunks instead of recomputing them — and its tokens are identical
    to the cold path's (and to the solo offline run)."""
    rs = np.random.RandomState(2)
    shared = _prompt(rs, 12)
    a = np.concatenate([shared, _prompt(rs, 3)])
    b = np.concatenate([shared, _prompt(rs, 5)])
    with InferenceServer(CFG, PARAMS, slots=1, queue=8,
                         prefill_chunk=4) as srv:
        res_a = srv.result(srv.submit(a, max_tokens=5, temperature=0.7,
                                      seed=2), timeout=300)
        res_b = srv.result(srv.submit(b, max_tokens=5, temperature=0.7,
                                      seed=9), timeout=300)
        m = srv.metrics()
    np.testing.assert_array_equal(
        res_a.tokens, _ref(a, 5, temperature=0.7, seed=2))
    np.testing.assert_array_equal(
        res_b.tokens, _ref(b, 5, temperature=0.7, seed=9))
    # request b's first 3 chunks (12 tokens) came from a's retired row
    assert m["prefix_cache"]["hit_tokens"] == 12, m["prefix_cache"]
    assert m["prefix_cache"]["hits"] == 1
    assert 0 < m["prefix_hit_rate"] < 1
    assert m["prefix_cache_bytes"] > 0


def test_prefix_budget_zero_disables_reuse():
    """serve_prefix_mb = 0 turns reuse off entirely: no hits, no cached
    bytes, tokens still identical."""
    rs = np.random.RandomState(3)
    shared = _prompt(rs, 12)
    a = np.concatenate([shared, _prompt(rs, 3)])
    b = np.concatenate([shared, _prompt(rs, 5)])
    with InferenceServer(CFG, PARAMS, slots=1, queue=8, prefill_chunk=4,
                         prefix_mb=0.0) as srv:
        res_a = srv.result(srv.submit(a, max_tokens=4), timeout=300)
        res_b = srv.result(srv.submit(b, max_tokens=4), timeout=300)
        m = srv.metrics()
    np.testing.assert_array_equal(res_a.tokens, _ref(a, 4))
    np.testing.assert_array_equal(res_b.tokens, _ref(b, 4))
    assert m["prefix_hit_rate"] == 0.0
    assert m["prefix_cache_bytes"] == 0
    assert m["prefix_cache"] is None


def test_trie_refcount_and_lru_eviction():
    """PrefixCache mechanics, driven directly: shared chunks become
    shared nodes, an interior node's refcount counts its children (so
    eviction unwinds chains leaf first), LRU picks the coldest
    evictable node, and eviction shortens later matches."""
    eng = DecodeEngine(CFG, PARAMS, slots=1, prefill_chunk=4)
    node_bytes = 2 * CFG.n_layer * CFG.n_head * 4 * (CFG.feat
                                                     // CFG.n_head) * 4
    cache = PrefixCache(eng, budget_bytes=3 * node_bytes)
    rs = np.random.RandomState(4)
    a = _prompt(rs, 12)                     # 3 complete chunks
    assert cache.insert_from_row(0, a) == 3
    assert cache.chunks == 3 and cache.nbytes == 3 * node_bytes
    chain = cache.match(np.concatenate([a, a[:1]]))
    assert len(chain) == 3
    # interior nodes are pinned by their children; only the tail is
    # evictable
    assert [n.refs for n in chain] == [1, 1, 0]
    # a second prompt sharing chunks 0-1 with a different chunk 2 adds
    # ONE node -> over budget -> the LRU evictable leaf (a's tail, older
    # than b's fresh tail) is dropped
    b = np.concatenate([a[:8], _prompt(rs, 4)])
    assert cache.insert_from_row(0, b) == 1
    assert cache.evictions == 1
    assert cache.chunks == 3 and cache.nbytes == 3 * node_bytes
    assert len(cache.match(np.concatenate([a, a[:1]]))) == 2
    assert len(cache.match(np.concatenate([b, b[:1]]))) == 3
    # shrinking the budget unwinds the remaining chain leaf first — the
    # root chunk survives to the end
    cache.budget = node_bytes
    assert cache.evict_to_budget() == 2
    assert cache.chunks == 1
    (root_node,) = cache.match(np.concatenate([a[:4], a[:1]]))
    assert root_node.tokens == tuple(int(t) for t in a[:4])
    assert root_node.refs == 0
    # a chain larger than the WHOLE budget is truncated up front — it
    # must not flush warm entries for a tail eviction would trim anyway
    small = PrefixCache(eng, budget_bytes=2 * node_bytes)
    assert small.insert_from_row(0, _prompt(rs, 16)) == 2   # of 4 chunks
    assert small.chunks == 2 and small.evictions == 0
    # budget 0 = disabled: no lookups, no inserts
    off = PrefixCache(eng, budget_bytes=0)
    assert not off.enabled
    assert off.match(a) == [] and off.insert_from_row(0, a) == 0


# ------------------------------------------- compiled-program bounding
def test_chunk_signatures_bounded_under_mixed_lengths():
    """The acceptance bound: >= 30 distinct prompt lengths through the
    chunked path compile <= 4 prefill/chunk signatures (here: exactly
    one), asserted via the engine's RecompileGuard."""
    rs = np.random.RandomState(5)
    with InferenceServer(CFG, PARAMS, slots=4, queue=40, prefill_chunk=4,
                         prefix_mb=0.0, recompile_limit=4) as srv:
        handles = [srv.submit(_prompt(rs, n), max_tokens=1)
                   for n in range(2, 32)]         # 30 distinct lengths
        for h in handles:
            assert srv.result(h, timeout=300).status == "ok"
        sigs = srv._engine.prefill_signatures
    assert 1 <= len(sigs) <= 4, sigs


def test_whole_prompt_guard_trips_naming_the_drifting_dimension():
    """The legacy path under the same guard: each new prompt length is a
    new compiled program, and the limit trips with the drifting
    dimension named (CXN205 via analysis/recompile.py)."""
    eng = DecodeEngine(CFG, PARAMS, slots=1, prefill_chunk=0,
                       recompile_limit=2)
    rs = np.random.RandomState(6)
    key = np.asarray(jax.random.PRNGKey(0), np.uint32)
    eng.prefill(0, _prompt(rs, 3), key, 0.0, 0, 1.0)
    eng.prefill(0, _prompt(rs, 4), key, 0.0, 0, 1.0)
    with pytest.raises(LintError, match="n_prompt"):
        eng.prefill(0, _prompt(rs, 5), key, 0.0, 0, 1.0)
    assert len(eng.prefill_signatures) == 3


# --------------------------------------------------------- scheduling
def test_long_prompt_prefill_does_not_stall_active_row():
    """Interleaving: while a 40-token prompt prefills 2 tokens per pass
    (20 chunk steps), an already-active row keeps ticking — it finishes
    its whole generation BEFORE the long prompt produces its first
    token, instead of convoying behind the prefill."""
    rs = np.random.RandomState(7)
    a = _prompt(rs, 3)
    b = _prompt(rs, 40)
    with InferenceServer(CFG, PARAMS, slots=2, queue=8, prefill_chunk=2,
                         prefix_mb=0.0) as srv:
        ha = srv.submit(a, max_tokens=6)
        deadline = time.time() + 60
        while ha.status in ("queued", "prefill") and time.time() < deadline:
            time.sleep(0.005)               # wait until a is decoding
        hb = srv.submit(b, max_tokens=2)
        res_a = srv.result(ha, timeout=300)
        res_b = srv.result(hb, timeout=300)
    assert res_a.status == "ok" and res_b.status == "ok"
    np.testing.assert_array_equal(res_a.tokens, _ref(a, 6))
    np.testing.assert_array_equal(res_b.tokens, _ref(b, 2))
    # a retired strictly before b's prefill completed
    assert ha.done_t < hb.first_token_t, (ha.done_t, hb.first_token_t)


def test_scheduler_crash_without_restart_budget_fails_exactly_once():
    """A device-call failure mid-pass with the restart budget OFF
    (serve_max_restarts=0) must finish every in-flight request exactly
    once with the typed EngineFailedError status: the scheduler retires
    the ones it tracks, the journal sweep only touches untracked ones —
    no double finish, no double count. (With the default budget the
    same crash RECOVERS instead — tests/test_resilience.py.)"""
    import threading

    from cxxnet_tpu.serve import EngineFailedError
    rs = np.random.RandomState(8)
    srv = InferenceServer(CFG, PARAMS, slots=2, queue=8, prefill_chunk=4,
                          max_restarts=0)
    boom = RuntimeError("injected chunk failure")
    submitted = threading.Event()

    def exploding(*a, **kw):
        # hold the crash until every submit has landed — otherwise the
        # scheduler thread can race the submit loop, shut the server
        # down, and turn later submits into AdmissionErrors (a pre-
        # existing flake this event removes; the crash still happens
        # mid-pass with requests admitted, which is the point)
        submitted.wait(30)
        raise boom

    srv._engine.prefill_chunk = exploding
    handles = [srv.submit(_prompt(rs, 9), max_tokens=4) for _ in range(3)]
    submitted.set()
    results = [srv.result(h, timeout=60) for h in handles]
    assert [r.status for r in results] == ["error"] * 3
    assert all("serve_max_restarts" in r.error for r in results)
    assert srv.health()["state"] == "FAILED"
    with pytest.raises(EngineFailedError):
        srv.submit(_prompt(rs, 4))
    srv.shutdown(drain=False)
    m = srv.metrics()
    assert m["requests"]["error"] == 3, m["requests"]
    assert m["requests"]["submitted"] == 3
    assert m["resilience"]["restarts"] == 1


# --------------------------------------------------------- step audit
def test_chunk_step_lint_specs_fully_aliased():
    """lint_specs passes on the chunk step: prefill, chunk-prefill AND
    tick executables keep both donated caches aliased (pinned with
    donate=True on the CPU mesh, the test_lint idiom)."""
    from cxxnet_tpu.analysis import audit_serve_engine
    eng = DecodeEngine(CFG, PARAMS, slots=2, prefill_chunk=4)
    report, infos = audit_serve_engine(eng, n_prompt=5, donate=True)
    assert report.ok(), report.format()
    labels = [i["label"] for i in infos]
    assert labels == ["serve_prefill", "serve_prefill_chunk", "serve_tick"]
    for info in infos:
        assert info["donated"] == 2 and info["aliased"] == 2, info
