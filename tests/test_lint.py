"""cxn-lint: graph/config lint (pass 1), compiled-step audit (pass 2),
recompilation guard, and the CLI/tools surfaces (doc/lint.md)."""

import glob
import gzip
import os
import struct

import numpy as np
import pytest

from cxxnet_tpu.analysis import (LintError, RULES, audit_jit, audit_net,
                                 audit_serve_engine, lint_config_file,
                                 lint_config_text)
from cxxnet_tpu.io.data import DataBatch
from cxxnet_tpu.nnet.net import Net
from cxxnet_tpu.utils.config import tokenize

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

NET_CFG = """
netconfig = start
layer[+1:fc1] = fullc:fc1
  nhidden = 16
layer[+1] = relu
layer[+1:fc2] = fullc:fc2
  nhidden = 4
layer[+0] = softmax
netconfig = end
input_shape = 1,1,8
batch_size = 16
"""


def _net(extra=""):
    net = Net(tokenize(NET_CFG + extra))
    net.init_model()
    return net


# ---------------------------------------------------------------- pass 1
@pytest.mark.parametrize("conf", sorted(
    glob.glob(os.path.join(_REPO, "example", "*", "*.conf"))),
    ids=lambda p: os.path.relpath(p, _REPO))
def test_all_example_configs_lint_clean(conf):
    """Every shipped example config must produce zero findings — the
    linter's no-false-positives contract on real configs."""
    result = lint_config_file(conf)
    assert result.report.ok() and not result.report.warnings(), \
        "\n" + result.report.format()


def test_typo_key_did_you_mean():
    r = lint_config_text(NET_CFG + "bacth_size = 32\n", path="t.conf")
    f = [x for x in r.report.findings if x.rule == "CXN101"]
    assert len(f) == 1 and "bacth_size" in f[0].message
    assert "did you mean 'batch_size'" in f[0].message
    assert f[0].path == "t.conf" and f[0].line == 12
    assert not r.report.ok()


def test_typo_key_in_iterator_section_scoped():
    cfg = ("data = train\niter = mnist\n  path_img = x\n  shufle = 1\n"
           "iter = end\n" + NET_CFG)
    r = lint_config_text(cfg)
    f = [x for x in r.report.findings if x.rule == "CXN101"]
    assert len(f) == 1 and "shufle" in f[0].message and f[0].line == 4
    assert "did you mean 'shuffle'" in f[0].message


def test_typo_layer_scoped_key():
    cfg = NET_CFG.replace("  nhidden = 16", "  nhiden = 16")
    r = lint_config_text(cfg)
    msgs = [x.message for x in r.report.findings if x.rule == "CXN101"]
    assert any("nhiden" in m and "'fullc' layer" in m
               and "did you mean 'nhidden'" in m for m in msgs)


def test_dead_node_and_unreachable_layer():
    cfg = """
netconfig = start
layer[+1:fc1] = fullc:fc1
  nhidden = 8
layer[fc1->stub] = fullc:deadfc
  nhidden = 3
layer[fc1->out] = fullc:fc2
  nhidden = 4
layer[+0] = softmax
netconfig = end
input_shape = 1,1,8
batch_size = 8
"""
    r = lint_config_text(cfg, path="dead.conf")
    f = [x for x in r.report.findings if x.rule == "CXN103"]
    assert len(f) == 1 and f[0].layer == "deadfc" and f[0].line == 5
    assert "unreachable layer" in f[0].message


def test_shape_mismatch_reports_layer_and_line():
    cfg = """
netconfig = start
layer[0->a] = max_pooling
  kernel_size = 4
  stride = 4
layer[a->b] = conv:cv1
  kernel_size = 5
  nchannel = 8
layer[+0] = softmax
netconfig = end
input_shape = 3,8,8
batch_size = 8
"""
    r = lint_config_text(cfg, path="shape.conf")
    f = [x for x in r.report.findings if x.rule == "CXN102"]
    assert f and f[0].layer == "cv1" and f[0].line == 6
    assert not r.report.ok()


def test_share_shape_mismatch():
    cfg = """
netconfig = start
layer[+1:fc1] = fullc:fc1
  nhidden = 8
layer[fc1->h2] = fullc:fc2
  nhidden = 6
layer[h2->h3] = share[fc1]
layer[+0] = softmax
netconfig = end
input_shape = 1,1,8
batch_size = 8
"""
    r = lint_config_text(cfg)
    f = [x for x in r.report.findings if x.rule == "CXN104"]
    assert f and "do not match the primary layer" in f[0].message


def test_metric_binding_unknown_field_and_node():
    cfg = NET_CFG + "metric[nolabel] = error\nmetric[label,ghost] = error\n"
    r = lint_config_text(cfg)
    f = [x for x in r.report.findings if x.rule == "CXN105"]
    assert len(f) == 2
    assert any("nolabel" in x.message for x in f)
    assert any("ghost" in x.message for x in f)


def test_trainer_value_validation():
    r = lint_config_text(NET_CFG + "dist_feed = bogus\n")
    f = [x for x in r.report.findings if x.rule == "CXN107"]
    assert f and "dist_feed" in f[0].message and f[0].line == 12


def test_unknown_metric_name_caught():
    r = lint_config_text(NET_CFG + "metric = acuracy\n")
    f = [x for x in r.report.findings if x.rule == "CXN107"]
    assert f and "acuracy" in f[0].message


def test_lint_ignore_suppresses_rule():
    cfg = """
netconfig = start
layer[+1:fc1] = fullc:fc1
  nhidden = 8
layer[fc1->stub] = fullc:deadfc
  nhidden = 3
layer[fc1->out] = fullc:fc2
  nhidden = 4
layer[+0] = softmax
netconfig = end
input_shape = 1,1,8
batch_size = 8
lint_ignore = CXN103
"""
    r = lint_config_text(cfg)
    assert r.report.ok(), r.report.format()
    assert r.report.n_suppressed == 1


def test_unterminated_quote_carries_line():
    r = lint_config_text("a = 1\nb = 2\npath = \"unterminated\n",
                         path="q.conf")
    f = r.report.findings
    assert len(f) == 1 and f[0].rule == "CXN100" and f[0].line == 3
    assert "unterminated" in f[0].message


def test_rule_catalog_covers_all_emitted_rules():
    for rid, (sev, _) in RULES.items():
        assert sev in ("error", "warning")
        assert rid.startswith("CXN")


# ---------------------------------------------------------------- pass 2
def test_donation_audit_all_four_net_steps_aliased_on_cpu():
    """Regression pin: every donated buffer of all four Net jit steps
    keeps its input_output_alias in the CPU executable."""
    net = _net("update_period = 2\n")
    report, infos = audit_net(net)
    assert report.ok(), report.format()
    by = {i["label"]: i for i in infos}
    for label in ("net_update", "net_accum", "net_apply"):
        assert by[label]["donated"] > 0, label
        assert by[label]["aliased"] == by[label]["donated"], (label, by)
    assert by["net_forward"]["donated"] == 0


def test_dropped_donation_is_reported_with_reason():
    import jax
    import jax.numpy as jnp
    from jax import ShapeDtypeStruct as SDS
    f = jax.jit(lambda a, b: (a * b).sum(), donate_argnums=(0,))
    findings, info = audit_jit(
        f, (SDS((4, 4), jnp.float32), SDS((4, 4), jnp.float32)), "toy",
        donate_argnums=(0,))
    assert len(findings) == 1 and findings[0].rule == "CXN201"
    assert "dropped at lowering" in findings[0].message
    assert info["donated"] == 1 and info["aliased"] == 0


def test_collective_budget():
    net = _net()
    report, _ = audit_net(net, collective_budget=0)
    # pure-DP on the 8-device CPU mesh: the grad all-reduce must show up
    over = [f for f in report.findings if f.rule == "CXN204"]
    assert over, "expected the data-parallel all-reduce to break budget 0"
    report2, infos = audit_net(net, collective_budget=64)
    assert not [f for f in report2.findings if f.rule == "CXN204"]
    assert any(sum(i["collectives"].values()) > 0 for i in infos)


def test_serve_engine_audit_donation():
    import jax
    from cxxnet_tpu.models.gpt import GPTConfig, gpt_init
    from cxxnet_tpu.serve.engine import DecodeEngine
    cfg = GPTConfig(vocab_size=64, feat=32, n_head=2, n_layer=2, seq_len=32)
    params = gpt_init(jax.random.PRNGKey(0), cfg)
    eng = DecodeEngine(cfg, params, slots=4)
    report, infos = audit_serve_engine(eng, n_prompt=4, donate=True)
    assert report.ok(), report.format()
    # prefill, the chunk-prefill step (engine default chunking), and the
    # tick must each keep both donated KV caches aliased
    assert [i["label"] for i in infos] == ["serve_prefill",
                                           "serve_prefill_chunk",
                                           "serve_tick"]
    for info in infos:
        assert info["donated"] == 2 and info["aliased"] == 2, info


# --------------------------------------------------- recompilation guard
def test_recompile_guard_trips_on_varied_static_shape():
    net = _net("lint_recompile_limit = 1\n")
    rs = np.random.RandomState(0)

    def batch(b):
        return DataBatch(rs.rand(b, 1, 1, 8).astype(np.float32),
                         np.zeros((b, 1), np.float32))

    net.update(batch(16))
    net.update(batch(16))           # same signature: no trip
    assert len(net._jit_update.signatures) == 1
    net.batch_size = 8              # deliberately vary the static shape
    with pytest.raises(LintError, match="CXN205.*net_update"):
        net.update(batch(8))


def test_recompile_guard_off_by_default():
    net = _net()
    assert not hasattr(net._jit_update, "signatures")


# ------------------------------------------------------------- surfaces
BAD_CONF = """
netconfig = start
layer[+1:fc1] = fullc:fc1
  nhidden = 16
layer[fc1->stub] = fullc:deadfc
  nhidden = 3
layer[fc1->a] = max_pooling
  kernel_size = 4
  stride = 4
layer[a->b] = conv:cv1
  kernel_size = 5
  nchannel = 8
layer[+0] = softmax
netconfig = end
input_shape = 3,8,8
bacth_size = 100
batch_size = 8
"""


def test_cli_task_lint_exits_nonzero_and_reports_all(tmp_path, capfd):
    from cxxnet_tpu.cli import main
    conf = tmp_path / "bad.conf"
    conf.write_text(BAD_CONF)
    rc = main([str(conf), "task=lint"])
    out = capfd.readouterr().out
    assert rc == 1
    # the misspelled key, the dead layer, and the shape mismatch all
    # report with file:line
    assert "%s:16: error CXN101" % conf in out and "bacth_size" in out
    assert "%s:5: error CXN103" % conf in out
    assert "%s:10: error CXN102" % conf in out


def test_cli_task_lint_clean_config(tmp_path, capfd):
    from cxxnet_tpu.cli import main
    conf = tmp_path / "ok.conf"
    conf.write_text(NET_CFG)
    assert main([str(conf), "task=lint"]) == 0
    assert "clean" in capfd.readouterr().out


def test_cli_task_lint_compile_audit(tmp_path, capfd):
    from cxxnet_tpu.cli import main
    conf = tmp_path / "ok.conf"
    conf.write_text(NET_CFG)
    assert main([str(conf), "task=lint", "lint_compile=1"]) == 0
    out = capfd.readouterr().out
    assert "net_update" in out and "donated" in out


def test_tools_cxn_lint_all_examples():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "cxn_lint", os.path.join(_REPO, "tools", "cxn_lint.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main(["--all-examples", "--quiet"]) == 0


def test_tools_cxn_lint_threads():
    """Tier-1 gate: the CXN3xx concurrency lint (pass 3) must stay
    clean over the whole package — a guarded write drifting out from
    under its lock fails CI here, not in a fleet-suite deadlock."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "cxn_lint", os.path.join(_REPO, "tools", "cxn_lint.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main(["--threads", "--quiet"]) == 0


def test_wrapper_lint_surface():
    from cxxnet_tpu import wrapper
    net = wrapper.Net(cfg=NET_CFG + "bacth_size = 1\n")
    report = net.lint()
    assert any(f.rule == "CXN101" for f in report.findings)
    ok = wrapper.Net(cfg=NET_CFG)
    ok.init_model()
    report = ok.lint(compile=True)
    assert report.ok(), report.format()


# --------------------------------------------------- CXN_LINT runtime hook
def _write_idx(tmp, images, labels):
    pi, pl = str(tmp / "img.gz"), str(tmp / "lab.gz")
    n, r, c = images.shape
    with gzip.open(pi, "wb") as f:
        f.write(struct.pack(">iiii", 2051, n, r, c))
        f.write(images.tobytes())
    with gzip.open(pl, "wb") as f:
        f.write(struct.pack(">ii", 2049, labels.shape[0]))
        f.write(labels.astype(np.uint8).tobytes())
    return pi, pl


TRAIN_CONF = """
data = train
iter = mnist
    path_img = "{img}"
    path_label = "{lab}"
iter = end
""" + NET_CFG + """
input_shape = 1,1,64
num_round = 1
save_model = 0
silent = 1
dev = cpu
"""


def test_cxn_lint_runtime_hook(tmp_path, capfd, monkeypatch):
    """CXN_LINT=1 runs both passes at startup, logs findings through the
    profiler, and installs the recompilation guard — the run itself
    completes."""
    from cxxnet_tpu.cli import LearnTask
    rs = np.random.RandomState(0)
    img, lab = _write_idx(tmp_path,
                          (rs.rand(64, 8, 8) * 255).astype(np.uint8),
                          rs.randint(0, 4, 64))
    conf = tmp_path / "t.conf"
    conf.write_text(TRAIN_CONF.format(img=img, lab=lab))
    monkeypatch.setenv("CXN_LINT", "1")
    task = LearnTask()
    assert task.run([str(conf)]) == 0
    err = capfd.readouterr().err
    assert "cxn-lint: graph lint clean" in err
    assert "cxn-lint: step audit clean" in err
    assert "net_update: donated" in err
    # the hook installed the default recompilation guard
    assert hasattr(task.net._jit_update, "signatures")


def test_cxn_lint_strict_fails_on_errors(tmp_path, capfd, monkeypatch):
    from cxxnet_tpu.cli import LearnTask
    conf = tmp_path / "bad.conf"
    conf.write_text(BAD_CONF)
    monkeypatch.setenv("CXN_LINT", "2")
    with pytest.raises(LintError, match="graph lint failed"):
        LearnTask().run([str(conf)])


def test_recompile_guard_non_strict_logs_and_continues(capfd):
    net = _net("lint_recompile_limit = 1\nlint_recompile_strict = 0\n")
    rs = np.random.RandomState(0)

    def batch(b):
        return DataBatch(rs.rand(b, 1, 1, 8).astype(np.float32),
                         np.zeros((b, 1), np.float32))

    net.update(batch(16))
    net.batch_size = 8
    net.update(batch(8))            # trips, but only logs
    assert "CXN205" in capfd.readouterr().err
    assert len(net._jit_update.signatures) == 2


def test_cli_reports_tokenizer_error_as_finding(tmp_path, capfd):
    """A config that cannot even tokenize must exit with a formatted
    CXN100 file:line finding, not a traceback — whatever the task."""
    from cxxnet_tpu.cli import main
    conf = tmp_path / "broken.conf"
    conf.write_text("a = 1\npath = 'unterminated\n")
    assert main([str(conf), "task=lint"]) == 1
    err = capfd.readouterr().err
    assert "%s:2: error CXN100" % conf in err
    assert "unterminated" in err
