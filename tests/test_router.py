"""Replicated serving router (serve/router.py): prefix- and health-
aware routing, chaos-kill failover with bit-identical replay on
survivors, drain as live-request migration, and the merged metrics
surface. The obs-side merge property (router payload == union of
per-replica observations) is pinned in tests/test_obs.py.
"""

import numpy as np
import pytest

import jax

from cxxnet_tpu.models.gpt import GPTConfig, gpt_decode, gpt_init
from cxxnet_tpu.serve import (EngineFailedError, InferenceServer,
                              QueueFullError, ServeRouter)
from cxxnet_tpu.serve.resilience import STATE_FAILED

CFG = GPTConfig(vocab_size=32, seq_len=48, n_layer=2, n_head=2, feat=16,
                n_microbatch=1)
PARAMS = gpt_init(jax.random.PRNGKey(5), CFG)


def _prompt(rs, n):
    return rs.randint(0, CFG.vocab_size, (n,)).astype(np.int32)


def _ref(prompt, max_new, temperature=0.0, seed=0):
    rng = jax.random.PRNGKey(seed) if temperature > 0 else None
    return np.asarray(gpt_decode(PARAMS, prompt[None], max_new, CFG,
                                 temperature=temperature, rng=rng))[0]


KW = dict(slots=2, queue=16, prefill_chunk=4)


@pytest.fixture(scope="module", autouse=True)
def _warm_programs():
    """Compile the serve programs once (module-level lru caches)."""
    rs = np.random.RandomState(99)
    with InferenceServer(CFG, PARAMS, **KW) as srv:
        h = srv.submit(_prompt(rs, 6), max_tokens=4)
        assert srv.result(h, timeout=300).status == "ok"


def test_router_validation():
    with pytest.raises(ValueError, match="replicas"):
        ServeRouter(CFG, PARAMS, replicas=0, **KW)
    with pytest.raises(ValueError, match="policy"):
        ServeRouter(CFG, PARAMS, replicas=2, policy="best", **KW)
    with pytest.raises(ValueError, match="registries"):
        ServeRouter(CFG, PARAMS, replicas=2, registry=object(), **KW)
    with pytest.raises(ValueError, match="chaos"):
        ServeRouter(CFG, PARAMS, replicas=2, chaos=("a", "b", "c"), **KW)


def test_router_identity_and_spread():
    """Mixed traffic over 2 replicas: every stream equals the solo
    oracle (the replicas serve the same export) and both replicas see
    work."""
    rs = np.random.RandomState(0)
    jobs = [(_prompt(rs, n), 5) for n in (6, 11, 3, 17, 7, 9)]
    refs = [_ref(p, m) for p, m in jobs]
    with ServeRouter(CFG, PARAMS, replicas=2, **KW) as rt:
        hs = [rt.submit(p, max_tokens=m) for p, m in jobs]
        for (p, m), h, r in zip(jobs, hs, refs):
            res = rt.result(h, timeout=300)
            assert res.status == "ok"
            assert np.array_equal(res.tokens, r)
        assert sum(rt.routed) == len(jobs)
        assert all(n > 0 for n in rt.routed)
        m = rt.metrics()
        assert m["requests"]["completed"] == len(jobs)
        assert m["failovers"] == 0


def test_router_prefix_affinity_converges():
    """Two distinct shared-prefix families: once a family's first
    request lands somewhere, the rest of the family follows it (the
    replica whose paged trie holds the prefix serves the zero-copy
    hit)."""
    rs = np.random.RandomState(1)
    fam_a = _prompt(rs, 12)
    fam_b = _prompt(rs, 12)
    with ServeRouter(CFG, PARAMS, replicas=2, **KW) as rt:
        homes = {}
        for fam, key in ((fam_a, "a"), (fam_b, "b")):
            for i in range(3):
                p = np.concatenate([fam, _prompt(rs, 2 + i)])
                h = rt.submit(p, max_tokens=4)
                assert rt.result(h, timeout=300).status == "ok"
                homes.setdefault(key, []).append(h.replica)
        # each family converges on one replica after its first request
        for key, seen in homes.items():
            assert len(set(seen[1:])) == 1, homes
        assert rt.affinity_hits >= 4
        # and the affinity actually fed the paged prefix cache: the
        # home replica's trie served hit tokens for the family
        hits = sum(s.metrics()["prefix_cache"]["hit_tokens"]
                   for s in rt.servers)
        assert hits > 0


def test_router_rr_policy_round_robins():
    rs = np.random.RandomState(2)
    with ServeRouter(CFG, PARAMS, replicas=2, policy="rr", **KW) as rt:
        hs = [rt.submit(_prompt(rs, 6), max_tokens=3) for _ in range(6)]
        for h in hs:
            assert rt.result(h, timeout=300).status == "ok"
        assert rt.routed == [3, 3]
        assert rt.affinity_hits == 0


def test_router_failover_chaos_kill_bit_identical_and_monotone():
    """The acceptance pin: a replica chaos-killed mid-stream (restart
    budget 0 -> FAILED) has its in-flight requests replayed on the
    survivor with greedy streams bit-identical to the fault-free
    oracle, and the aggregate counters stay monotone."""
    rs = np.random.RandomState(3)
    jobs = [(_prompt(rs, n), 8) for n in (6, 11, 3, 17, 7, 9)]
    refs = [_ref(p, m) for p, m in jobs]
    with ServeRouter(CFG, PARAMS, replicas=2, max_restarts=0,
                     chaos=("tick_raise@4", ""), **KW) as rt:
        before = rt.metrics()["requests"]
        hs = [rt.submit(p, max_tokens=m) for p, m in jobs]
        for (p, m), h, r in zip(jobs, hs, refs):
            res = rt.result(h, timeout=300)
            assert res.status == "ok", (res.status, res.error)
            assert np.array_equal(res.tokens, r), (res.tokens, r)
        after = rt.metrics()["requests"]
        assert rt.failovers > 0
        assert rt.servers[0].health()["state"] == STATE_FAILED
        # monotone aggregates: nothing went backwards, every submitted
        # request reached ok on SOME replica exactly once
        for k in after:
            assert after[k] >= before[k], (k, before, after)
        assert after["completed"] == len(jobs)
        # the survivor's replay counter saw the migrations
        assert rt.servers[1].metrics()["resilience"]["replayed"] \
            == rt.failovers
        # new submissions keep working, routed onto the survivor
        h = rt.submit(jobs[0][0], max_tokens=4)
        assert h.replica == 1
        assert rt.result(h, timeout=300).status == "ok"
        # router health: degraded fleet but still serving
        assert rt.health()["state"] == "SERVING"


def test_router_failover_preserves_sampled_schedule():
    """A sampled request migrated mid-stream resumes on the pinned
    fold_in schedule: with speculation off its tokens equal the solo
    sampled oracle even across the kill."""
    rs = np.random.RandomState(4)
    jobs = [(_prompt(rs, 7), 8, dict(temperature=0.8, seed=i))
            for i in range(4)]
    refs = [_ref(p, m, temperature=0.8, seed=ov["seed"])
            for p, m, ov in jobs]
    with ServeRouter(CFG, PARAMS, replicas=2, max_restarts=0,
                     chaos=("tick_raise@3", ""), **KW) as rt:
        hs = [rt.submit(p, max_tokens=m, **ov) for p, m, ov in jobs]
        for h, r in zip(hs, refs):
            res = rt.result(h, timeout=300)
            assert res.status == "ok"
            assert np.array_equal(res.tokens, r)


def test_router_drain_migrates_live_requests():
    rs = np.random.RandomState(5)
    jobs = [(_prompt(rs, 9), 24) for _ in range(4)]
    refs = [_ref(p, m) for p, m in jobs]
    with ServeRouter(CFG, PARAMS, replicas=2, **KW) as rt:
        hs = [rt.submit(p, max_tokens=m) for p, m in jobs]
        victims = [h for h in hs if h.replica == 0]
        moved = rt.drain_replica(0)
        assert moved == len([h for h in victims])
        assert rt.drain_migrations == moved
        for h, r in zip(hs, refs):
            res = rt.result(h, timeout=300)
            assert res.status == "ok"
            assert np.array_equal(res.tokens, r)
        # replica 0 is out of rotation: everything new lands on 1
        h = rt.submit(jobs[0][0], max_tokens=3)
        assert h.replica == 1
        assert rt.result(h, timeout=300).status == "ok"
        assert rt.health()["routable"] == [False, True]


def test_router_replicas_on_disjoint_device_blocks():
    """With enough local devices, replica i's engine lives on its own
    device block — tp=1 replicas get one device each (placement-only
    mesh), tp=2 replicas get disjoint 2-device meshes — so an N-device
    rig actually runs N engines in parallel instead of stacking them
    on device 0."""
    rs = np.random.RandomState(8)
    for tp in (0, 2):
        with ServeRouter(CFG, PARAMS, replicas=2, tp=tp, **KW) as rt:
            devs = [frozenset(s._engine.cache_k.devices())
                    for s in rt.servers]
            assert devs[0].isdisjoint(devs[1]), (tp, devs)
            assert all(len(d) == max(1, tp) for d in devs)
            h = rt.submit(_prompt(rs, 6), max_tokens=4)
            res = rt.result(h, timeout=300)
            assert res.status == "ok"
            assert np.array_equal(res.tokens, _ref(h.prompt, 4))


def test_router_drain_migrates_under_active_waiters():
    """The drain race: callers already blocked in result() while
    drain_replica aborts their replica must get the MIGRATED outcome
    (bit-identical tokens from the survivor), never the intermediate
    'cancelled' the abort resolves their first incarnation with."""
    import threading
    rs = np.random.RandomState(9)
    jobs = [(_prompt(rs, 9), 24) for _ in range(4)]
    refs = [_ref(p, m) for p, m in jobs]
    with ServeRouter(CFG, PARAMS, replicas=2, **KW) as rt:
        hs = [rt.submit(p, max_tokens=m) for p, m in jobs]
        out = [None] * len(hs)

        def wait(i, h):
            out[i] = rt.result(h, timeout=300)

        ths = [threading.Thread(target=wait, args=(i, h))
               for i, h in enumerate(hs)]
        for t in ths:
            t.start()
        rt.drain_replica(0)
        for t in ths:
            t.join(300)
        for res, r in zip(out, refs):
            assert res is not None and res.status == "ok", res
            assert np.array_equal(res.tokens, r)


def test_router_all_replicas_failed_is_typed():
    rs = np.random.RandomState(6)
    with ServeRouter(CFG, PARAMS, replicas=2, max_restarts=0,
                     chaos=("tick_raise@1", "tick_raise@1"), **KW) as rt:
        hs = [rt.submit(_prompt(rs, 6), max_tokens=6) for _ in range(2)]
        # both engines die on their first tick; with no survivor the
        # typed error surfaces instead of a hang
        res = [rt.result(h, timeout=300) for h in hs]
        assert all(r.status == "error" for r in res)
        assert rt.health()["state"] == STATE_FAILED
        with pytest.raises(EngineFailedError):
            rt.submit(_prompt(rs, 5), max_tokens=3)


def test_cli_task_serve_replicated_tp(tmp_path, capfd, monkeypatch):
    """task=serve with serve_replicas=2 AND serve_tp=2 — the full
    composition through the CLI: outputs in submission order and
    token-identical to task=generate on the same snapshot, router
    summary on stderr."""
    import io as _io

    from cxxnet_tpu.cli import LearnTask
    from cxxnet_tpu.models import gpt_lm_config

    corpus = tmp_path / "corpus.bin"
    toks = np.tile(np.arange(16, dtype=np.uint16), 40)
    corpus.write_bytes(toks.tobytes())
    conf = tmp_path / "gpt.conf"
    cfg = gpt_lm_config(seq_len=16, vocab_size=32, feat=16, nhead=2,
                        nblock=2, batch_size=8, dev="cpu:0", eta=0.2)
    conf.write_text("""
data = train
iter = lm
    path_data = "%s"
    token_dtype = uint16
    seq_len = 16
    stride = 8
iter = end
%s
num_round = 1
save_model = 1
model_dir = %s
""" % (corpus, cfg, tmp_path / "models"))
    assert LearnTask().run([str(conf)]) == 0
    model = tmp_path / "models" / "0001.model"

    prompts = tmp_path / "p.txt"
    gen_out = tmp_path / "g.txt"
    want = []
    for line in ("0 1 2 3", "4 5 6 7 8"):
        prompts.write_text(line + "\n")
        assert LearnTask().run([
            str(conf), "task=generate", "model_in=%s" % model,
            "prompt_file=%s" % prompts, "num_gen=4",
            "generate_out=%s" % gen_out]) == 0
        want.append(gen_out.read_text().split())
    capfd.readouterr()

    monkeypatch.setattr("sys.stdin",
                        _io.StringIO("0 1 2 3\n4 5 6 7 8\n"))
    assert LearnTask().run([
        str(conf), "task=serve", "model_in=%s" % model, "num_gen=4",
        "serve_slots=2", "serve_queue=4", "serve_prefill_chunk=4",
        "serve_replicas=2", "serve_tp=2"]) == 0
    out, err = capfd.readouterr()
    rows = [l.split() for l in out.strip().splitlines()
            if l and l[0].isdigit()]
    assert rows == want
    assert "2 replicas (prefix router)" in err
    assert "tp=2" in err
    assert "over 2 replicas" in err


def test_router_queue_full_spills_to_peer():
    """Backpressure on the preferred replica spills the submit to the
    other one instead of bouncing the client."""
    rs = np.random.RandomState(7)
    fam = _prompt(rs, 8)
    with ServeRouter(CFG, PARAMS, replicas=2, slots=1, queue=1,
                     prefill_chunk=4) as rt:
        # pin the family onto replica A, then flood it: affinity says A
        # but A's queue of 1 fills — later submits must land on B, and
        # only when BOTH queues are full does QueueFullError surface
        hs = []
        with pytest.raises(QueueFullError):
            for i in range(12):
                hs.append(rt.submit(
                    np.concatenate([fam, _prompt(rs, 2)]), max_tokens=16))
        assert len(set(h.replica for h in hs)) == 2
        for h in hs:
            assert rt.result(h, timeout=300).status == "ok"
