"""Async training feed (io/device_prefetch.py) + on-device train metrics.

Pins the round-6 tentpole's contracts:
- the prefetcher yields exactly the synchronous path's batches, in order
  (single-process, and the fake 2-process ordering guards);
- the bounded queue really backpressures (at most depth+1 placements ahead
  of the consumer) and close() mid-epoch tears the producer down;
- with eval_train on, a training round performs O(log boundaries)
  device->host syncs — not O(steps) — and the on-device (sum, count)
  accumulators match the per-step host accumulation bit-for-bit on the
  digits-style model;
- prefetched and synchronous CLI training produce identical eval lines.
"""

import threading
import time

import numpy as np
import pytest

from cxxnet_tpu.io import create_iterator
from cxxnet_tpu.io.device_prefetch import DevicePrefetcher
from cxxnet_tpu.nnet.net import Net
from cxxnet_tpu.utils.config import tokenize
from cxxnet_tpu.cli import LearnTask
import cxxnet_tpu.io.device_prefetch as dp
import cxxnet_tpu.nnet.net as nnet_net

from test_train_e2e import CONF, synth_mnist  # noqa: F401 (fixture)


def _train_iter(synth_mnist, batch_size=64):  # noqa: F811
    return create_iterator([
        ("iter", "mnist"),
        ("path_img", "%s/train-img.gz" % synth_mnist),
        ("path_label", "%s/train-lab.gz" % synth_mnist),
        ("shuffle", "1"),
        ("batch_size", str(batch_size)),
        ("input_shape", "1,1,64"),
    ])


def _trainer_cfg(synth_mnist, tmp_path, extra=()):  # noqa: F811
    pairs = [p for p in tokenize(CONF.format(d=synth_mnist, md=tmp_path))
             if p[0] not in ("data", "eval", "iter", "path_img",
                             "path_label", "shuffle")]
    return pairs + list(extra)


def _net(synth_mnist, tmp_path, extra=()):  # noqa: F811
    net = Net(_trainer_cfg(synth_mnist, tmp_path, extra))
    net.init_model()
    return net


def test_prefetcher_matches_sync_batches_and_order(synth_mnist, tmp_path):  # noqa: F811
    """Identical data/label/order to the synchronous placement path,
    across two epochs (epoch rewind included)."""
    net = _net(synth_mnist, tmp_path)

    sync_it = _train_iter(synth_mnist)
    sync = []
    for _ in range(2):
        sync_it.before_first()
        while sync_it.next():
            db = net.place_batch(sync_it.value())
            sync.append((np.asarray(db.data), np.asarray(db.label)))

    feed = DevicePrefetcher(net.place_batch, _train_iter(synth_mnist),
                            depth=2)
    try:
        pre = []
        for _ in range(2):
            feed.before_first()
            while feed.next():
                db = feed.value()
                pre.append((np.asarray(db.data), np.asarray(db.label)))
    finally:
        feed.close()

    assert len(sync) == len(pre) == 16      # 512 imgs / 64 x 2 epochs
    for (sd, sl), (pd, pl) in zip(sync, pre):
        np.testing.assert_array_equal(sd, pd)
        np.testing.assert_array_equal(sl, pl)


def test_bounded_queue_backpressure(synth_mnist, tmp_path):  # noqa: F811
    """The producer may run at most depth ahead of the consumer, plus the
    one batch blocked in the queue put."""
    net = _net(synth_mnist, tmp_path)
    feed = DevicePrefetcher(net.place_batch, _train_iter(synth_mnist),
                            depth=1)
    try:
        feed.before_first()
        deadline = time.time() + 2.0
        while feed.placed < 2 and time.time() < deadline:
            time.sleep(0.01)
        time.sleep(0.2)                      # would overrun here if unbounded
        assert feed.placed <= 2, \
            "queue depth 1 let %d placements run ahead" % feed.placed
        n = 0
        while feed.next():
            n += 1
        assert n == 8 and feed.placed == 8
    finally:
        feed.close()


def test_close_mid_epoch_joins_producer(synth_mnist, tmp_path):  # noqa: F811
    net = _net(synth_mnist, tmp_path)
    feed = DevicePrefetcher(net.place_batch, _train_iter(synth_mnist),
                            depth=1)
    feed.before_first()
    assert feed.next() and feed.next()       # mid-epoch
    thread = feed._thread
    feed.close()
    assert thread is not None and not thread.is_alive()
    feed.close()                             # idempotent
    assert not [t for t in threading.enumerate()
                if t.name.startswith("cxn-device-prefetch")]


def test_multihost_single_feed_guard(synth_mnist, tmp_path, monkeypatch):  # noqa: F811
    """Fake 2-process mode: a second live prefetcher must be refused —
    placement order across processes is only provable with one producer."""
    net = _net(synth_mnist, tmp_path)
    monkeypatch.setattr(dp, "is_multi_host", lambda: True)
    feed = DevicePrefetcher(net.place_batch, _train_iter(synth_mnist),
                            depth=1)
    try:
        with pytest.raises(RuntimeError, match="identical across processes"):
            DevicePrefetcher(net.place_batch, _train_iter(synth_mnist),
                             depth=1)
    finally:
        feed.close()
    feed2 = DevicePrefetcher(net.place_batch, _train_iter(synth_mnist),
                             depth=1)
    feed2.close()


def test_multihost_epoch_count_check(synth_mnist, tmp_path, monkeypatch):  # noqa: F811
    """Fake 2-process mode with CXN_PREFETCH_CHECK=1: the epoch boundary
    all-gathers the consumed-batch count (divergent feeds must fail loudly,
    not place mismatched slices)."""
    net = _net(synth_mnist, tmp_path)
    calls = []
    monkeypatch.setattr(dp, "is_multi_host", lambda: True)
    monkeypatch.setattr(dp, "multihost_assert_equal",
                        lambda row, what: calls.append((list(row), what)))
    monkeypatch.setenv("CXN_PREFETCH_CHECK", "1")
    feed = DevicePrefetcher(net.place_batch, _train_iter(synth_mnist),
                            depth=2)
    try:
        feed.before_first()
        while feed.next():
            pass
        assert not calls                     # first epoch: nothing to check
        feed.before_first()                  # boundary -> count verified
        assert calls == [([8.0], "DevicePrefetcher epoch batch count")]
    finally:
        feed.close()


def test_device_metrics_match_host_bit_for_bit(synth_mnist, tmp_path):  # noqa: F811
    """On-device (sum, count) accumulation == per-step host accumulation,
    bit for bit, on the digits-style MLP (metric = error: integer-valued
    sums, exactly representable — the acceptance bar)."""
    net_dev = _net(synth_mnist, tmp_path)
    net_host = _net(synth_mnist, tmp_path, extra=[("device_metrics", "0")])
    assert net_dev._metric_mode == "device"
    assert net_host._metric_mode == "host"

    it = _train_iter(synth_mnist)
    it.before_first()
    while it.next():
        b = it.value()
        net_dev.update(b)
        net_host.update(b)

    net_dev._fold_train_accum()
    dev_acc = [(m.sum_metric, m.cnt_inst)
               for m in net_dev.train_metrics.metrics]
    host_acc = [(m.sum_metric, m.cnt_inst)
                for m in net_host.train_metrics.metrics]
    assert dev_acc == host_acc == [(dev_acc[0][0], 512)]
    assert dev_acc[0][0] == int(dev_acc[0][0])   # error sums are counts
    # and the printed train line agrees end to end
    assert net_dev.evaluate(None, "train") == \
        net_host.evaluate(None, "train")


def test_train_round_syncs_O_log_boundaries(synth_mnist, tmp_path,  # noqa: F811
                                            monkeypatch):
    """eval_train=1 must not fetch per step: zero local_rows/np.asarray
    pulls during the round, exactly one accumulator fold per log
    boundary."""
    fetches = []
    real_local_rows = nnet_net.local_rows
    monkeypatch.setattr(nnet_net, "local_rows",
                        lambda a: (fetches.append(1),
                                   real_local_rows(a))[1])
    net = _net(synth_mnist, tmp_path)
    assert net._metric_mode == "device"
    it = _train_iter(synth_mnist)
    it.before_first()
    steps = 0
    while it.next():
        net.update(it.value())
        steps += 1
    assert steps == 8
    assert fetches == []                     # O(steps) syncs are gone
    assert net.metric_sync_count == 0
    line = net.evaluate(None, "train")
    assert "train-error:" in line
    assert net.metric_sync_count == 1        # one fold per log boundary
    assert fetches == []
    # the loss stays lazily fetchable (its own single sync on demand)
    assert np.isfinite(net.last_loss())


def test_prefetched_vs_sync_cli_identical(synth_mnist, tmp_path, capfd):  # noqa: F811
    """prefetch_to_device = 2 (default) and = 0 must train identically —
    same batches, same order, same math -> identical eval lines."""
    def run(tag, prefetch):
        md = tmp_path / ("m_%s" % tag)
        conf = tmp_path / ("%s.conf" % tag)
        conf.write_text(CONF.format(d=synth_mnist, md=md))
        task = LearnTask()
        assert task.run([str(conf), "num_round=2", "max_round=2",
                         "save_model=0",
                         "prefetch_to_device=%d" % prefetch]) == 0
        err = capfd.readouterr().err
        return [l for l in err.splitlines() if l.startswith("[")]

    sync_lines = run("sync", 0)
    pre_lines = run("pre", 2)
    assert len(sync_lines) == 2
    assert sync_lines == pre_lines


@pytest.mark.slow
def test_prefetch_stress_many_epochs(synth_mnist, tmp_path):  # noqa: F811
    """Many-epoch soak of the async feed: epoch rewinds, queue reuse, and
    the device metric accumulator across 30 rounds (excluded from tier-1
    via the slow marker)."""
    net = _net(synth_mnist, tmp_path)
    feed = DevicePrefetcher(net.place_batch, _train_iter(synth_mnist),
                            depth=2)
    try:
        total = 0
        for _ in range(30):
            feed.before_first()
            while feed.next():
                net.update(feed.value())
                total += 1
        assert total == 30 * 8
        line = net.evaluate(None, "train")
        assert "train-error:" in line and net.metric_sync_count == 1
        assert np.isfinite(net.last_loss())
    finally:
        feed.close()
