"""Pallas kernels vs XLA reference numerics (interpret mode on CPU) —
the PairTest idea applied to custom kernels (SURVEY §4.1)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import cxxnet_tpu.ops.pallas_kernels as pk
from cxxnet_tpu.ops.attention import full_attention


@pytest.fixture(autouse=True)
def interpret_mode():
    old = pk._INTERPRET
    pk._INTERPRET = True
    yield
    pk._INTERPRET = old


def _lrn_ref(x, n, alpha, beta, knorm):
    pad_lo = (n - 1) // 2
    sq = jax.lax.reduce_window(
        x * x, 0.0, jax.lax.add, (1, 1, 1, n), (1, 1, 1, 1),
        ((0, 0), (0, 0), (0, 0), (pad_lo, n - 1 - pad_lo)))
    return x * (knorm + (alpha / n) * sq) ** (-beta)


@pytest.mark.parametrize("n", [3, 5])
def test_lrn_fused_matches_reduce_window(n):
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(2, 4, 4, 16).astype(np.float32))
    ref = _lrn_ref(x, n, 1e-4, 0.75, 1.0)
    out = pk.lrn_fused(x, n, 1e-4, 0.75, 1.0, row_tile=8)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_lrn_fused_row_padding():
    rs = np.random.RandomState(1)
    x = jnp.asarray(rs.randn(3, 5, 7, 8).astype(np.float32))  # 105 rows
    ref = _lrn_ref(x, 5, 2e-4, 0.5, 2.0)
    out = pk.lrn_fused(x, 5, 2e-4, 0.5, 2.0, row_tile=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_matches_full(causal):
    rs = np.random.RandomState(2)
    q, k, v = (jnp.asarray(rs.randn(2, 32, 2, 8).astype(np.float32))
               for _ in range(3))
    ref = full_attention(q, k, v, causal=causal)
    out = pk.flash_attention(q, k, v, causal, 8, 8)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal,bq,bk", [
    (True, 8, 8),      # causal, square blocks
    (False, 8, 8),     # non-causal: n_run=n_blocks / lo=0 branches
    (True, 16, 8),     # asymmetric blocks in both backward kernels
    (False, 8, 16),
])
def test_flash_attention_gradients(causal, bq, bk):
    rs = np.random.RandomState(3)
    q, k, v = (jnp.asarray(rs.randn(1, 16, 2, 8).astype(np.float32))
               for _ in range(3))
    g_ref = jax.grad(lambda a, b, c: (
        full_attention(a, b, c, causal=causal) ** 2).sum(), (0, 1, 2))(q, k, v)
    g_out = jax.grad(lambda a, b, c: (
        pk.flash_attention(a, b, c, causal, bq, bk) ** 2).sum(),
        (0, 1, 2))(q, k, v)
    for a, b in zip(g_out, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_lrn_fused_gradients_match_reference():
    rs = np.random.RandomState(4)
    x = jnp.asarray(rs.randn(2, 3, 3, 8).astype(np.float32))
    g_ref = jax.grad(lambda a: (_lrn_ref(a, 5, 1e-4, 0.75, 2.0) ** 2).sum())(x)
    g_out = jax.grad(lambda a: (pk.lrn_fused(a, 5, 1e-4, 0.75, 2.0, 8) ** 2).sum())(x)
    np.testing.assert_allclose(np.asarray(g_out), np.asarray(g_ref),
                               rtol=1e-4, atol=1e-5)


def test_lrn_layer_uses_pallas_when_enabled():
    """The lrn layer must route through the fused kernel under the gate and
    still produce reference numerics (PairTest-style)."""
    from cxxnet_tpu.layers import create_layer
    from cxxnet_tpu.graph import LayerSpec
    from cxxnet_tpu.layers.base import ApplyContext
    spec = LayerSpec("lrn", "l", [0], [1])
    layer = create_layer(spec, [("local_size", "5"), ("alpha", "0.001"),
                                ("beta", "0.75"), ("knorm", "2.0")])
    layer.infer_shapes([(8, 4, 4)])
    rs = np.random.RandomState(5)
    x = jnp.asarray(rs.randn(2, 4, 4, 8).astype(np.float32))
    ctx = ApplyContext(train=False, rng=None)
    out_pallas = layer.apply({}, [x], ctx)[0]       # _INTERPRET fixture on
    pk._INTERPRET = False                            # force jnp path on CPU
    out_ref = layer.apply({}, [x], ctx)[0]
    np.testing.assert_allclose(np.asarray(out_pallas), np.asarray(out_ref),
                               rtol=1e-5, atol=1e-6)


def test_flash_block_selection():
    """Adaptive default: 512-blocks only when the sequence is a multiple
    of 512; explicit requests clamp to the sequence."""
    from cxxnet_tpu.ops.pallas_kernels import _flash_block

    assert _flash_block(1024, None) == 512
    assert _flash_block(4096, None) == 512
    assert _flash_block(768, None) == 256       # 256-aligned but not 512
    assert _flash_block(128, None) == 128       # tiny ring chunks clamp
    assert _flash_block(1024, 8) == 8           # explicit wins
    assert _flash_block(4, 8) == 4              # explicit clamps to n


def test_flash_streaming_family_matches_reference(monkeypatch):
    """Long sequences use the streaming kernels (K/V blocks on the grid,
    scratch accumulators). Force them at a small size and pin fwd+grads
    against the exact XLA formulation."""
    import jax
    import jax.numpy as jnp

    from cxxnet_tpu.ops import pallas_kernels as pk
    from cxxnet_tpu.ops.attention import full_attention

    # 0 forces every size onto the streaming family (_flash_resident is
    # n*d-budgeted, so a small positive cutoff could still admit tiny
    # test shapes into the resident family)
    monkeypatch.setattr(pk, "_FLASH_RESIDENT_MAX", 0)
    rs = np.random.RandomState(3)
    q, k, v = (jnp.asarray(rs.randn(2, 32, 2, 8).astype(np.float32))
               for _ in range(3))
    for causal in (False, True):
        ref, vjp_ref = jax.vjp(
            lambda q, k, v: full_attention(q, k, v, causal=causal), q, k, v)
        out, vjp_out = jax.vjp(
            lambda q, k, v: pk.flash_attention(q, k, v, causal, 8, 8),
            q, k, v)
        assert float(jnp.max(jnp.abs(out - ref))) < 1e-5
        g = jnp.asarray(rs.randn(*q.shape).astype(np.float32))
        for a, b in zip(vjp_out(g), vjp_ref(g)):
            assert float(jnp.max(jnp.abs(a - b))) < 1e-5


def test_flash_attention_rejects_unaligned_seq():
    """Grids use floor division — a sequence not divisible by the block
    size must raise rather than silently leave tail rows uninitialized."""
    rs = np.random.RandomState(11)
    q, k, v = (jnp.asarray(rs.randn(1, 24, 2, 8).astype(np.float32))
               for _ in range(3))
    with pytest.raises(ValueError, match="divisible"):
        pk.flash_attention(q, k, v, False, 16, 8)
    with pytest.raises(ValueError, match="divisible"):
        jax.grad(lambda a: pk.flash_attention(a, k, v, False, 8, 16).sum())(q)


def _unfused_rlp(x, n, alpha, beta, knorm, k, s, relu=True):
    r = jnp.maximum(x, 0) if relu else x
    pad_lo = (n - 1) // 2
    sq = jax.lax.reduce_window(r * r, 0.0, jax.lax.add, (1, 1, 1, n),
                               (1, 1, 1, 1),
                               ((0, 0), (0, 0), (0, 0),
                                (pad_lo, n - 1 - pad_lo)))
    norm = knorm + (alpha / n) * sq
    u = r * norm ** (-beta)
    return jax.lax.reduce_window(u, -jnp.inf, jax.lax.max, (1, k, k, 1),
                                 (1, s, s, 1),
                                 ((0, 0), (0, 0), (0, 0), (0, 0)))


@pytest.mark.parametrize("shape,k,s", [
    ((4, 13, 13, 16), 3, 2),    # AlexNet-style overlap, odd size
    ((2, 9, 9, 8), 3, 2),
    ((2, 8, 8, 8), 2, 2),       # non-overlapping
])
def test_fused_relu_lrn_maxpool_matches_chain(shape, k, s):
    rs = np.random.RandomState(7)
    x = jnp.asarray(rs.randn(*shape).astype(np.float32))
    args = (5, 1e-4, 0.75, 1.0)
    assert pk.fused_relu_lrn_maxpool_supported(shape, 5, k, s, 0, None)
    out_f = pk.fused_relu_lrn_maxpool(x, True, *args, k, s)
    out_r = _unfused_rlp(x, *args, k, s)
    np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_r),
                               rtol=1e-5, atol=1e-5)
    g_f = jax.grad(lambda a: (
        pk.fused_relu_lrn_maxpool(a, True, *args, k, s) ** 2).sum())(x)
    g_r = jax.grad(lambda a: (_unfused_rlp(a, *args, k, s) ** 2).sum())(x)
    np.testing.assert_allclose(np.asarray(g_f), np.asarray(g_r),
                               rtol=1e-4, atol=1e-4)


def test_fused_relu_lrn_maxpool_tie_semantics():
    """On ties the fused backward credits EVERY maximal element with the
    full window gradient — the reference unpool expression
    ((src == pooled) * grad, mshadow pooling backward), which XLA's
    select-and-scatter (first-max-only) does not reproduce."""
    # constant input, no lrn effect (alpha=0): pure relu+maxpool chain
    x = jnp.ones((1, 4, 4, 8), jnp.float32)
    k, s = 2, 2
    g = jax.grad(lambda a: pk.fused_relu_lrn_maxpool(
        a, True, 1, 0.0, 0.75, 1.0, k, s).sum())(x)
    # every element ties in its (non-overlapping) window -> grad 1 each
    np.testing.assert_allclose(np.asarray(g), np.ones_like(np.asarray(g)))


@pytest.mark.parametrize("causal", [False, True])
def test_flash_bhnd_packed_residual_grads(causal):
    """d=64 engages the packed-residual backward (qo/kv lane-pair
    packing); gradients must match the token-major flash path."""
    rs = np.random.RandomState(13)
    b, h, n, d = 2, 3, 32, 64
    q, k, v = (jnp.asarray(rs.randn(b, h, n, d).astype(np.float32))
               for _ in range(3))
    assert pk._flash_pack_res(d, n)
    tr = lambda t: jnp.transpose(t, (0, 2, 1, 3))
    g_ref = jax.grad(lambda a, bb, c: (
        pk.flash_attention(tr(a), tr(bb), tr(c), causal, 8, 8) ** 2)
        .sum(), (0, 1, 2))(q, k, v)
    g_out = jax.grad(lambda a, bb, c: (
        pk.flash_attention_bhnd(a, bb, c, causal, 8, 8) ** 2)
        .sum(), (0, 1, 2))(q, k, v)
    for a, b2 in zip(g_out, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b2),
                                   rtol=2e-4, atol=2e-4)


def test_layernorm_fused_matches_reference():
    def ref_ln(x, g, b, eps=1e-5):
        xf = x.astype(jnp.float32)
        mean = xf.mean(-1, keepdims=True)
        var = ((xf - mean) ** 2).mean(-1, keepdims=True)
        return ((xf - mean) * jax.lax.rsqrt(var + eps) * g + b).astype(
            x.dtype)

    rs = np.random.RandomState(21)
    for shape in [(16, 128), (2, 8, 256)]:
        x = jnp.asarray(rs.randn(*shape).astype(np.float32))
        g = jnp.asarray(rs.randn(shape[-1]).astype(np.float32))
        b = jnp.asarray(rs.randn(shape[-1]).astype(np.float32))
        assert pk.layernorm_fused_supported(shape, x.dtype)
        np.testing.assert_allclose(
            np.asarray(pk.layernorm_fused(x, g, b)),
            np.asarray(ref_ln(x, g, b)), rtol=2e-5, atol=2e-5)
        grads = lambda fn: jax.grad(
            lambda a, gg, bb: (fn(a, gg, bb) ** 2).sum(), (0, 1, 2))(x, g, b)
        for got, want in zip(grads(pk.layernorm_fused), grads(ref_ln)):
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       rtol=1e-4, atol=1e-4)


def test_cached_attention_matches_reference(monkeypatch):
    """The decode cached-attention kernel (one kernel per (batch, head):
    scores -> causal mask -> softmax -> PV) vs the jnp chain, interpret
    mode, several mask positions."""
    import cxxnet_tpu.ops.pallas_kernels as pk
    monkeypatch.setattr(pk, "_INTERPRET", True)
    rs = np.random.RandomState(0)
    b, h, s, d = 2, 3, 24, 64
    q = jnp.asarray(rs.randn(b, h, 1, d).astype(np.float32))
    ck = jnp.asarray(rs.randn(b, h, s, d).astype(np.float32))
    cv = jnp.asarray(rs.randn(b, h, s, d).astype(np.float32))
    for pos in (0, 5, s - 1):
        got = pk.cached_attention(q, ck, cv, jnp.int32(pos))
        sc = jnp.einsum("bhqd,bhkd->bhqk", q, ck) / (d ** 0.5)
        mask = jnp.arange(s)[None, None, None, :] <= pos
        w = jax.nn.softmax(jnp.where(mask, sc, -1e30), axis=-1)
        ref = jnp.einsum("bhqk,bhkd->bhqd", w, cv)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


def make_decode_reference(rs, nl=3, b=2, nh=4, s=64, d=64, pos=21,
                          dtype="float32"):
    """Shared fixture for the fused-decode differentials (also imported by
    tools/tpu_smoke.py): stacked block weights, inputs, and the jnp
    layer-stack reference function."""
    import jax.numpy as jnp
    from jax import lax
    from cxxnet_tpu.models.gpt import _attn_cached, _block_core_fusedqkv

    f = nh * d
    m = 4 * f
    blocks = {k: jnp.asarray(rs.randn(nl, *shp) * sc, jnp.float32)
              for k, shp, sc in (
                  ("ln1_g", (f,), 0.1), ("ln1_b", (f,), 0.1),
                  ("w_qkv", (f, 3 * f), 0.05), ("b_qkv", (3 * f,), 0.02),
                  ("w_proj", (f, f), 0.05), ("b_proj", (f,), 0.02),
                  ("ln2_g", (f,), 0.1), ("ln2_b", (f,), 0.1),
                  ("w_mlp1", (f, m), 0.05), ("b_mlp1", (m,), 0.02),
                  ("w_mlp2", (m, f), 0.05), ("b_mlp2", (f,), 0.02))}
    blocks["ln1_g"] = blocks["ln1_g"] + 1.0
    blocks["ln2_g"] = blocks["ln2_g"] + 1.0
    dt = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
    h = jnp.asarray(rs.randn(b, 1, f) * 0.5, dt)
    ck = jnp.asarray(rs.randn(nl, b, nh, s, d) * 0.3, dt)
    cv = jnp.asarray(rs.randn(nl, b, nh, s, d) * 0.3, dt)

    def reference(bb, hh):
        def layer(carry_h, xs):
            p, ckl, cvl = xs

            def attn(q, k, v):
                kh = jnp.swapaxes(k, 1, 2)
                vh = jnp.swapaxes(v, 1, 2)
                ck2 = lax.dynamic_update_slice(ckl, kh, (0, 0, pos, 0))
                cv2 = lax.dynamic_update_slice(cvl, vh, (0, 0, pos, 0))
                return _attn_cached(q, ck2, cv2, pos), (ck2, cv2)

            out, (c1, c2) = _block_core_fusedqkv(p, carry_h, nh, attn,
                                                 lambda t: t)
            return out, (c1, c2)

        return jax.lax.scan(layer, hh, (bb, ck, cv))

    return blocks, h, ck, cv, pos, nh, reference


def test_fused_decode_step_matches_jnp(monkeypatch):
    """Whole-step fused decode kernel (grid over layers, h in scratch,
    window cache outputs) vs the jnp decode math, interpret mode."""
    from cxxnet_tpu.ops import pallas_kernels as pk

    monkeypatch.setattr(pk, "_INTERPRET", True)
    for b in (1, 2, 5):     # batch rows share each layer's weight fetch
        rs = np.random.RandomState(7)
        blocks, h, ck, cv, pos, nh, reference = make_decode_reference(rs, b=b)
        ref_h, (ref_ck, ref_cv) = reference(blocks, h)
        out, ck2, cv2 = pk.fused_decode_step(blocks, h, ck, cv, pos, nh)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref_h),
                                   rtol=2e-5, atol=2e-5, err_msg="b=%d" % b)
        np.testing.assert_allclose(np.asarray(ck2), np.asarray(ref_ck),
                                   rtol=2e-5, atol=2e-5, err_msg="b=%d" % b)
        np.testing.assert_allclose(np.asarray(cv2), np.asarray(ref_cv),
                                   rtol=2e-5, atol=2e-5, err_msg="b=%d" % b)


def test_fused_decode_step_int8_matches_dequant(monkeypatch):
    """int8 weight-streaming decode (round 5): the kernel fed int8
    weights + per-out-column scales must equal the SAME kernel fed the
    explicitly dequantized weights (the dequant multiply commutes with
    the contraction); and the quantizer's round-trip error stays within
    the symmetric-int8 bound."""
    from cxxnet_tpu.models.gpt import (QUANT_DECODE_PAIRS,
                                       _dequantize_decode_blocks,
                                       _quantize_decode_blocks)
    from cxxnet_tpu.ops import pallas_kernels as pk

    monkeypatch.setattr(pk, "_INTERPRET", True)
    rs = np.random.RandomState(11)
    blocks, h, ck, cv, pos, nh, _ = make_decode_reference(rs, b=2)
    qb = _quantize_decode_blocks(blocks)
    deq = _dequantize_decode_blocks(qb, dtype=blocks["w_qkv"].dtype)
    # quantizer bound: |w - q*s| <= s/2 per element
    for wk, sk in QUANT_DECODE_PAIRS:
        w = np.asarray(blocks[wk], np.float32)
        bound = np.asarray(qb[sk])[:, None, :] * 0.5 + 1e-7
        assert (np.abs(w - np.asarray(deq[wk], np.float32))
                <= bound).all(), wk
        assert qb[wk].dtype == jnp.int8
    out_q, ckq, cvq = pk.fused_decode_step(qb, h, ck, cv, pos, nh)
    out_r, ckr, cvr = pk.fused_decode_step(deq, h, ck, cv, pos, nh)
    np.testing.assert_allclose(np.asarray(out_q), np.asarray(out_r),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(ckq), np.asarray(ckr),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(cvq), np.asarray(cvr),
                               rtol=2e-5, atol=2e-5)


def test_fused_decode_step_head_folded(monkeypatch):
    """Head folding (round 5): with head=(lnf_g, lnf_b, w_head) the
    kernel emits the GREEDY next-token ids of final-LN + head-matmul +
    argmax — must equal the same computation applied to the unfolded
    kernel's hidden-state output, with identical cache windows."""
    from cxxnet_tpu.ops import pallas_kernels as pk

    monkeypatch.setattr(pk, "_INTERPRET", True)
    rs = np.random.RandomState(5)
    blocks, h, ck, cv, pos, nh, _ = make_decode_reference(rs, b=3)
    f = h.shape[-1]
    v = 48
    lnf_g = jnp.asarray(rs.randn(f).astype(np.float32) * 0.3 + 1)
    lnf_b = jnp.asarray(rs.randn(f).astype(np.float32) * 0.1)
    w_head = jnp.asarray(rs.randn(f, v).astype(np.float32) * 0.2)
    out_h, ck1, cv1 = pk.fused_decode_step(blocks, h, ck, cv, pos, nh)
    tok, ck2, cv2 = pk.fused_decode_step(blocks, h, ck, cv, pos, nh,
                                         head=(lnf_g, lnf_b, w_head))
    x = np.asarray(out_h, np.float32)
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    hl = (x - mu) / np.sqrt(var + 1e-5) * np.asarray(lnf_g) \
        + np.asarray(lnf_b)
    ref = (hl[:, 0] @ np.asarray(w_head)).argmax(-1)
    assert tok.shape == (3, 1) and tok.dtype == jnp.int32
    np.testing.assert_array_equal(np.asarray(tok)[:, 0], ref)
    np.testing.assert_allclose(np.asarray(ck1), np.asarray(ck2))
    np.testing.assert_allclose(np.asarray(cv1), np.asarray(cv2))
