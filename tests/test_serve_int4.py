"""Int4 weight streaming end-to-end (doc/serving.md "Int4 weights"):
packed nibbles, group-wise scales, and the fused Pallas dequant-matmul
through the serve programs.

The load-bearing invariants:

1. **pinned no-op when off** — the default engine/server holds
   full-precision weights (no uint8 planes, no scale groups, empty
   signature suffix); the whole pre-existing bit-identity corpus runs
   against exactly these defaults;
2. **the packing is exact** — pack -> unpack is the identity on int4
   codes, and quantize -> dequantize lands within the one contract;
3. **kernel == reference, bitwise** — ``int4_matmul`` in interpret mode
   is bit-identical to the XLA reference ``_qmat4_ref`` under an
   exactness-by-construction regime (integer activations, power-of-two
   scales: every op is exact in f32, so any divergence is structural,
   not rounding), grouped AND per-column, f32 AND bf16;
4. **accuracy under ONE contract** — ``w_int4_tolerance()`` bounds the
   lockstep greedy divergence and the sampled-mode chi-squared, and
   nothing in this file invents its own ad-hoc tolerance;
5. **hygiene** — int4 vs int8 vs full-precision engines count DISTINCT
   single RecompileGuard signatures (``/w=int4/g=<group>`` rides in the
   signature string), the step audit's CXN211 names any full-width
   unpacked int4 weight materialized where the fused kernel should be
   active (``int4=clean`` column), the device-memory ledger prices the
   weight pool at its PACKED bytes, and the autotune geometry key keyes
   on the weight stream.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cxxnet_tpu.models.gpt import (GPTConfig, INT4_GROUP_DEFAULT,
                                   QUANT_DECODE_PAIRS,
                                   _dequantize_decode_blocks_int4,
                                   _fuse_qkv_blocks, _int4_groups,
                                   _pack_int4, _qmat4_ref,
                                   _quantize_decode_blocks_int4,
                                   _unpack_int4, gpt_decode, gpt_init)
from cxxnet_tpu.ops import pallas_kernels as pk
from cxxnet_tpu.serve import DecodeEngine, InferenceServer, auto_num_blocks
from cxxnet_tpu.serve.engine import w_int4_tolerance, weight_stream_tag

CFG = GPTConfig(vocab_size=32, seq_len=48, n_layer=2, n_head=2, feat=16,
                n_microbatch=1)
PARAMS = gpt_init(jax.random.PRNGKey(5), CFG)
NB = auto_num_blocks(CFG, 2, 4)


def _prompt(rs, n):
    return rs.randint(0, CFG.vocab_size, (n,)).astype(np.int32)


def _admit(eng, slot, prompt, key, temp=0.0):
    """Drive a paged engine's chunk prefill by hand (reserve + chunk
    windows); returns the first sampled token."""
    tok = None
    for start in range(0, len(prompt), eng.chunk):
        end = min(start + eng.chunk, len(prompt))
        eng.reserve_window(slot, start, start + eng.chunk)
        buf = np.zeros(eng.chunk, np.int32)
        buf[:end - start] = prompt[start:end]
        tok = eng.prefill_chunk(slot, buf, start, end - start, key, temp,
                                0, 1.0)
    return int(tok)


def _tick_one(eng, slot, tok, pos, fold, key=None, temp=0.0):
    """One batched tick advancing only ``slot`` (other rows parked)."""
    b = eng.slots
    t = np.zeros(b, np.int32)
    t[slot] = tok
    p = np.full(b, eng.row_len - 1, np.int32)
    p[slot] = pos
    keys = np.zeros((b, 2), np.uint32)
    if key is not None:
        keys[slot] = key
    f = np.zeros(b, np.int32)
    f[slot] = fold
    nxt = eng.tick(t, p, keys, f, np.full(b, temp, np.float32),
                   np.zeros(b, np.int32), np.ones(b, np.float32))
    return int(nxt[slot])


# --------------------------------------------------- pinned no-op (off)
def test_defaults_are_pinned_noop():
    """With serve_int4_weights unset the engine holds full-precision
    weight planes (no uint8, no group-scale planes), an empty signature
    suffix, and the server reports the flag off — the structural half
    of the no-op pin (the token-identity half is every pre-existing
    serve suite, which runs against exactly these defaults)."""
    eng = DecodeEngine(CFG, PARAMS, 2, prefill_chunk=4, num_blocks=NB)
    assert not eng.int4_weights
    assert eng.int4_group == INT4_GROUP_DEFAULT
    assert eng.int4_formulation == ""
    assert eng._sig_suffix == ""
    for wk, sk in QUANT_DECODE_PAIRS:
        assert eng._blocks[wk].dtype != jnp.uint8
        assert sk not in eng._blocks
    with InferenceServer(CFG, PARAMS, slots=2, queue=4,
                         prefill_chunk=4) as srv:
        m = srv.metrics()
    assert m["int4_weights"] is False
    assert m["int4_formulation"] == ""


def test_validation():
    with pytest.raises(ValueError, match="mutually"):
        DecodeEngine(CFG, PARAMS, 2, prefill_chunk=4, num_blocks=NB,
                     int4_weights=True, int8_weights=True)
    with pytest.raises(ValueError, match="serve_int4_group"):
        DecodeEngine(CFG, PARAMS, 2, prefill_chunk=4, num_blocks=NB,
                     int4_weights=True, int4_group=-1)
    with pytest.raises(ValueError, match="mutually"):
        gpt_decode(PARAMS, jnp.zeros((1, 4), jnp.int32), 2, CFG,
                   int4_weights=True, int8_weights=True)
    with pytest.raises(ValueError, match="int4_group"):
        gpt_decode(PARAMS, jnp.zeros((1, 4), jnp.int32), 2, CFG,
                   int4_weights=True, int4_group=-2)


def test_int4_composes_with_tp_bit_identical():
    """ROADMAP 3c closed: ``serve_int4_weights=1`` with ``serve_tp=2``
    is accepted (shard-aware packing — nibble pairs never straddle a
    shard boundary) and the sharded int4 server's greedy stream is
    BIT-IDENTICAL to the single-device int4 server's. The sharded
    engine streams the XLA reference formulation (the in-tile Pallas
    unpack assumes the single-segment layout), counted under
    ``cxn_int4_fallback_total{reason="tp"}``."""
    if len(jax.devices()) < 2:
        pytest.skip("needs 2 local devices for a model-axis mesh")
    rs = np.random.RandomState(3)
    jobs = [(_prompt(rs, n), 8) for n in (5, 9, 14)]
    kw = dict(slots=2, prefill_chunk=4, num_blocks=NB, paged=True,
              int4_weights=True, int4_group=0)

    def serve(tp):
        srv = InferenceServer(CFG, PARAMS, **kw, **({"tp": tp} if tp else {}))
        try:
            hs = [srv.submit(p, max_tokens=m) for p, m in jobs]
            out = [srv.result(h, timeout=300) for h in hs]
            assert all(r.status == "ok" for r in out), \
                [(r.status, r.error) for r in out]
            return [r.tokens for r in out], srv.metrics()
        finally:
            srv.shutdown()

    solo, _ = serve(0)
    shard, m = serve(2)
    for a, b in zip(solo, shard):
        assert np.array_equal(a, b), (a, b)
    assert m["int4_weights"] and m["int4_formulation"] == ""


# ------------------------------------------------------- packing is exact
def test_pack_unpack_roundtrip_identity():
    """pack -> unpack is the identity on every int4 code, including the
    extremes (the offset-8 storage covers [-8, 7]; the quantizer emits
    [-7, 7])."""
    rs = np.random.RandomState(0)
    q = rs.randint(-7, 8, (3, 10, 12)).astype(np.int8)
    q[0, 0, :2] = (-7, 7)
    out = np.asarray(_unpack_int4(_pack_int4(jnp.asarray(q))))
    np.testing.assert_array_equal(out, q)


def test_quantize_dequantize_within_contract():
    """quantize -> dequantize of the fused block dict stays within the
    ONE tolerance contract, grouped and per-column, balanced ragged
    groups included; packed planes store TRUE k rows (no row padding),
    uint8, with the (L, G, n) f32 scale plane alongside."""
    tol = w_int4_tolerance()
    blocks = _fuse_qkv_blocks(PARAMS["blocks"])
    for group in (INT4_GROUP_DEFAULT, 0, 5):     # 5: ragged last group
        qb = _quantize_decode_blocks_int4(blocks, group)
        deq = _dequantize_decode_blocks_int4(qb)
        for wk, sk in QUANT_DECODE_PAIRS:
            w = np.asarray(blocks[wk], np.float32)
            L, k, n = w.shape
            assert qb[wk].dtype == jnp.uint8
            assert qb[wk].shape == (L, k, (n + 1) // 2)
            assert qb[sk].shape == (L, _int4_groups(k, group), n)
            err = np.abs(np.asarray(deq[wk]) - w).max()
            assert err <= tol["atol"] * np.abs(w).max(), (wk, group, err)


# ------------------------------------------- kernel == reference, bitwise
def _exact_case(rs, m, k, n, g, dtype):
    """Exactness-by-construction operands: integer-valued activations
    and power-of-two scales make every op in both formulations exact
    (codes and partials fit f32/bf16 mantissas, scaling is a pure
    exponent shift), so kernel-vs-reference equality is BITWISE — any
    difference is a structural divergence, not accumulated rounding."""
    x = jnp.asarray(rs.randint(-4, 5, (m, k)), dtype)
    q = jnp.asarray(rs.randint(-7, 8, (k, n)).astype(np.int8))
    packed = _pack_int4(q)
    scales = jnp.asarray(
        2.0 ** rs.randint(-3, 4, (g, n)).astype(np.float32))
    return x, packed, scales


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_kernel_bit_identical_to_reference(dtype):
    rs = np.random.RandomState(7)
    old = pk._INTERPRET
    pk._INTERPRET = True
    try:
        for m, k, n, g in ((8, 128, 256, 2), (8, 128, 256, 1),
                           (16, 64, 512, 4)):
            assert pk.int4_matmul_supported(m, k, n, g,
                                            itemsize=dtype(0).itemsize)
            x, packed, scales = _exact_case(rs, m, k, n, g, dtype)
            ker = np.asarray(pk.int4_matmul(x, packed, scales))
            ref = np.asarray(_qmat4_ref(x, packed, scales))
            np.testing.assert_array_equal(ker, ref, err_msg=str((m, k,
                                                                 n, g)))
    finally:
        pk._INTERPRET = old


def test_reference_matches_dequantized_matmul():
    """On random data the grouped reference agrees with the plain
    dequantize-then-matmul formulation to float rounding (the two sum
    the same products in a different order), ragged groups included —
    this ties ``_qmat4_ref`` to the dequantizer the accuracy contract
    is stated against."""
    rs = np.random.RandomState(8)
    for k, n, g in ((12, 10, 3), (10, 6, 4)):    # 10/4: ragged last group
        x = jnp.asarray(rs.randn(4, k).astype(np.float32))
        q = jnp.asarray(rs.randint(-7, 8, (k, n + n % 2)).astype(np.int8))
        g0 = -(-k // g)
        rows = np.minimum(np.arange(k) // g0, g - 1)
        scales = jnp.asarray(
            (0.01 + rs.rand(g, n)).astype(np.float32))
        deq = (np.asarray(q)[:, :n].astype(np.float32)
               * np.asarray(scales)[rows])
        ref = np.asarray(_qmat4_ref(x, _pack_int4(q), scales))
        np.testing.assert_allclose(ref, np.asarray(x) @ deq, rtol=1e-5,
                                   atol=1e-5)


def test_geometry_gate_and_fallback_reasons():
    """The support gate rejects ragged groups, odd packed widths, and
    over-VMEM tiles; the fallback reason names the rejecting half."""
    old = pk._INTERPRET
    pk._INTERPRET = True
    try:
        assert pk.int4_matmul_geometry_ok(8, 128, 256, 2)
        assert not pk.int4_matmul_geometry_ok(8, 130, 256, 4)  # ragged
        assert not pk.int4_matmul_geometry_ok(8, 128, 255, 1)  # odd n
        old_budget = pk._INT4_TILE_VMEM
        pk._INT4_TILE_VMEM = 1024
        try:
            assert not pk.int4_matmul_geometry_ok(8, 128, 256, 2)
            assert pk.int4_matmul_fallback_reason(8, 128, 256,
                                                  2) == "geometry"
        finally:
            pk._INT4_TILE_VMEM = old_budget
        assert pk.int4_matmul_fallback_reason(8, 128, 256, 2) == ""
    finally:
        pk._INTERPRET = old
    if jax.default_backend() != "tpu":
        assert pk.int4_matmul_fallback_reason(8, 128, 256,
                                              2) == "backend"


# ------------------------------------------------- accuracy contract
def test_int4_greedy_divergence_bounded():
    """Lockstep teacher-forced divergence: both engines fed the SAME
    context each step (the full-precision engine's greedy token), the
    fraction of steps where the int4 engine's argmax differs is bounded
    by the ONE contract, w_int4_tolerance()['greedy_flip']. A plumbing
    bug (wrong scale axis, swapped nibbles, garbage group map) flips
    essentially every step on this near-uniform tiny model."""
    rs = np.random.RandomState(1)
    prompt = _prompt(rs, 10)
    ref = DecodeEngine(CFG, PARAMS, 1, prefill_chunk=4, num_blocks=NB)
    q = DecodeEngine(CFG, PARAMS, 1, prefill_chunk=4, num_blocks=NB,
                     int4_weights=True)
    key = np.zeros((2,), np.uint32)
    t_ref = _admit(ref, 0, prompt, key)
    t_q = _admit(q, 0, prompt, key)
    steps = 24
    flips = int(t_ref != t_q)
    tok, pos = t_ref, len(prompt)
    for i in range(1, steps):
        ref.reserve_window(0, pos, pos + 1)
        q.reserve_window(0, pos, pos + 1)
        nxt_ref = _tick_one(ref, 0, tok, pos, i)
        nxt_q = _tick_one(q, 0, tok, pos, i)      # SAME forced context
        flips += int(nxt_ref != nxt_q)
        tok, pos = nxt_ref, pos + 1
    budget = w_int4_tolerance()["greedy_flip"]
    assert flips / steps <= budget, (flips, steps, budget)


def _chi2_crit(df, z=3.09):
    """Wilson-Hilferty upper-tail chi-squared quantile (z=3.09 ~ the
    contract's chi2_sig=1e-3)."""
    return df * (1 - 2 / (9 * df) + z * (2 / (9 * df)) ** 0.5) ** 3


def test_int4_sampled_chi_squared():
    """Sampled mode under int4 weights follows (statistically) the same
    first-token distribution as the full-precision engine at this
    sample size — int4 perturbs logits by a few percent, inside the
    two-sample chi-squared resolution, while a broken scale application
    shifts whole modes and fails hard."""
    rs = np.random.RandomState(2)
    prompt = _prompt(rs, 9)
    n = 600
    counts = {}
    for int4 in (False, True):
        eng = DecodeEngine(CFG, PARAMS, 1, prefill_chunk=4,
                           num_blocks=NB, int4_weights=int4)
        _admit(eng, 0, prompt, np.zeros((2,), np.uint32))
        pos = len(prompt)
        eng.reserve_window(0, pos, pos + 1)
        c = np.zeros(CFG.vocab_size)
        for s in range(n):
            key = np.asarray(jax.random.PRNGKey(s), np.uint32)
            c[_tick_one(eng, 0, int(prompt[-1]), pos, 1, key,
                        temp=1.0)] += 1
        counts[int4] = c
    a, b = counts[False], counts[True]
    keep = (a + b) > 0
    stat = float((((a - b) ** 2)[keep] / (a + b)[keep]).sum())
    df = int(keep.sum()) - 1
    assert df >= 2
    assert stat < _chi2_crit(df), (stat, df, a, b)


# --------------------------------------------------- int4 + speculative
def test_speculative_int4_composes_and_is_identity():
    """gpt_decode(speculative=..., int4_weights=True) composes, drafts
    fire, and the greedy speculative stream is bit-identical to the
    non-speculative int4 decode of the same prompt — the verify logits
    ARE the int4 tick's logits, packed weights included."""
    rs = np.random.RandomState(3)
    base = _prompt(rs, 6)
    prompt = jnp.asarray(np.concatenate([base, base, base]))[None]
    plain = np.asarray(gpt_decode(PARAMS, prompt, 8, CFG,
                                  int4_weights=True))
    spec = {"mode": "ngram", "spec_len": 3, "stats": {}}
    out = np.asarray(gpt_decode(PARAMS, prompt, 8, CFG, speculative=spec,
                                int4_weights=True))
    assert spec["stats"]["forwards"] >= 1
    np.testing.assert_array_equal(out, plain)


def test_int4_serving_identity_vs_own_oracle():
    """An int4-weights SERVER (paged, chunked, speculative) is
    stream-identical to the offline int4 decode of the same request —
    the weight quantization is one engine-build-time transform, not a
    per-program reinterpretation."""
    rs = np.random.RandomState(8)
    base = _prompt(rs, 6)
    prompt = np.concatenate([base, base])
    ref = np.asarray(gpt_decode(
        PARAMS, jnp.asarray(prompt)[None], 6, CFG, speculative=2,
        int4_weights=True))[0]
    with InferenceServer(CFG, PARAMS, slots=2, queue=4, prefill_chunk=4,
                         spec_mode="ngram", spec_len=2,
                         int4_weights=True) as srv:
        res = srv.result(srv.submit(prompt, max_tokens=6), timeout=300)
    assert res.status == "ok"
    np.testing.assert_array_equal(res.tokens, ref)


def test_per_column_group_serving_matches_hand_driven_engine():
    """serve_int4_group=0 (one scale group = per-out-column) through the
    full server is stream-identical to a hand-driven engine with the
    same grouping — the degenerate G=1 plumbing (scale plane (L, 1, n))
    serves end to end, deterministically."""
    rs = np.random.RandomState(12)
    prompt = _prompt(rs, 9)
    eng = DecodeEngine(CFG, PARAMS, 1, prefill_chunk=4, num_blocks=NB,
                       int4_weights=True, int4_group=0)
    assert eng._sig_suffix == "/w=int4/g=0"
    key = np.zeros((2,), np.uint32)
    toks = [_admit(eng, 0, prompt, key)]
    pos = len(prompt)
    for i in range(1, 5):
        eng.reserve_window(0, pos, pos + 1)
        toks.append(_tick_one(eng, 0, toks[-1], pos, i))
        pos += 1
    with InferenceServer(CFG, PARAMS, slots=2, queue=4, prefill_chunk=4,
                         prefix_mb=0.0, int4_weights=True,
                         int4_group=0) as srv:
        res = srv.result(srv.submit(prompt, max_tokens=5), timeout=300)
    assert res.status == "ok"
    np.testing.assert_array_equal(res.tokens[len(prompt):], toks)


# -------------------------------------------------------- hygiene pins
def test_recompile_signatures_distinct_per_weight_stream():
    """int4, int8 and full-precision engines in one process are three
    DISTINCT single signatures: the weight stream rides in the
    signature string (/w=int4/g=<group> carries the group width too —
    different groupings are different programs)."""
    rs = np.random.RandomState(10)
    engines = {
        "plain": DecodeEngine(CFG, PARAMS, 2, prefill_chunk=4,
                              num_blocks=NB, recompile_limit=1),
        "int8": DecodeEngine(CFG, PARAMS, 2, prefill_chunk=4,
                             num_blocks=NB, recompile_limit=1,
                             int8_weights=True),
        "int4": DecodeEngine(CFG, PARAMS, 2, prefill_chunk=4,
                             num_blocks=NB, recompile_limit=1,
                             int4_weights=True),
    }
    assert engines["int4"]._sig_suffix == "/w=int4/g=%d" \
        % INT4_GROUP_DEFAULT
    sigs = {}
    for name, eng in engines.items():
        for n in (5, 9):        # mixed lengths: still one signature
            eng.release_row(0)
            _admit(eng, 0, _prompt(rs, n), np.zeros((2,), np.uint32))
        assert len(eng.prefill_signatures) == 1
        sigs[name] = str(eng.prefill_signatures[0])
    assert len(set(sigs.values())) == 3
    assert "/w=int4/g=%d" % INT4_GROUP_DEFAULT in sigs["int4"]
    assert "int4" not in sigs["plain"] and "int4" not in sigs["int8"]


def test_weight_stream_tag_and_tuned_components():
    """The autotune geometry key carries the weight stream: an int4
    engine's tuned block width never shadows an int8/bf16 one's."""
    from cxxnet_tpu.analysis.aot_cache import tuned_components
    assert weight_stream_tag(False, False) == ""
    assert weight_stream_tag(True, False) == "int8"
    assert weight_stream_tag(False, True, 32) == "int4:g32"
    tags = ["", "int8", "int4:g64", "int4:g0"]
    comps = [tuned_components("h", 4, weights=t) for t in tags]
    assert comps[0]["w"] == "none"
    assert comps[2]["w"] == "int4:g64"
    assert len({tuple(sorted(c.items())) for c in comps}) == len(tags)


def test_int4_audit_clean_and_cxn211_detects():
    """With the kernel route armed (interpret mode stands in for the
    TPU backend) the int4 serve programs audit ``int4=clean`` — no
    full-width unpacked weight in HBM, no silent promotion — while a
    deliberate unpack-then-matmul trips CXN211 and a u8->f32 convert
    trips the widened CXN209."""
    from cxxnet_tpu.analysis import audit_serve_engine, format_step_info
    from cxxnet_tpu.analysis.step_audit import audit_jit
    bcfg = GPTConfig(vocab_size=32, seq_len=48, n_layer=2, n_head=2,
                     feat=16, n_microbatch=1, dtype="bfloat16")
    bparams = gpt_init(jax.random.PRNGKey(5), bcfg)
    old = pk._INTERPRET
    pk._INTERPRET = True
    try:
        eng = DecodeEngine(bcfg, bparams, 2, prefill_chunk=4,
                           abstract=True,
                           num_blocks=auto_num_blocks(bcfg, 2, 4),
                           int4_weights=True, int4_group=8, spec_len=3,
                           fused_attn=False)
        assert eng.int4_formulation == "fused"
        report, infos = audit_serve_engine(eng, donate=True)
    finally:
        pk._INTERPRET = old
    assert report.ok(), report.format()
    armed = [i for i in infos if "int4_dequants" in i]
    assert armed, "no program armed the CXN211 check"
    for info in armed:
        assert info["int4_dequants"] == 0
        assert info["int8_promotions"] == 0
        assert " int4=clean" in format_step_info(info)
    # positive control: a full-width dequant in front of the matmul is
    # exactly the HBM traffic the packing exists to remove
    k, n, g = 16, 48, 2
    rows = jnp.minimum(jnp.arange(k) // (k // g), g - 1)

    def bad(x, packed, scales):
        w = (_unpack_int4(packed).astype(jnp.float32)
             * scales[rows]).astype(x.dtype)
        return x @ w

    findings, info = audit_jit(
        jax.jit(bad),
        (jax.ShapeDtypeStruct((2, k), jnp.bfloat16),
         jax.ShapeDtypeStruct((k, n // 2), jnp.uint8),
         jax.ShapeDtypeStruct((g, n), jnp.float32)),
        "bad", check_int4={(k, n)})
    assert "CXN211" in [f.rule for f in findings]
    assert info["int4_dequants"] >= 1
    assert "materialized" in format_step_info(info)
    # the widened CXN209: a packed-nibble (u8) operand converted
    # straight to f32 inside a quantized step is a silent promotion
    findings, info = audit_jit(
        jax.jit(lambda a: a.astype(jnp.float32).sum()),
        (jax.ShapeDtypeStruct((4,), jnp.uint8),), "bad209",
        check_int8=True)
    assert [f.rule for f in findings] == ["CXN209"]
    assert info["int8_promotions"] == 1


def test_ledger_prices_packed_weight_pool():
    """cxn_device_bytes{pool=params} under int4 prices the PACKED
    representation: the weight pool shrinks by ~8x against the f32
    engine (4 bits vs 32 per block-weight element; the unquantized
    outer dict, biases and scale planes damp the pool-level ratio on
    this tiny config, where they are a large fraction of the bytes),
    and the engine's block dict really holds uint8 planes with
    (L, G, n) scales."""
    eng = DecodeEngine(CFG, PARAMS, 2, prefill_chunk=4, num_blocks=NB,
                       int4_weights=True, int4_group=8)
    for wk, sk in QUANT_DECODE_PAIRS:
        assert eng._blocks[wk].dtype == jnp.uint8
        k = eng._blocks[wk].shape[1]
        assert eng._blocks[sk].shape[1] == _int4_groups(k, 8)
    sizes = {}
    for int4 in (False, True):
        with InferenceServer(CFG, PARAMS, slots=2, queue=4,
                             prefill_chunk=4, num_blocks=NB,
                             int4_weights=int4) as srv:
            res = srv.result(srv.submit(np.arange(6, dtype=np.int32),
                                        max_tokens=3), timeout=300)
            assert res.status == "ok"
            m = srv.metrics()
            sizes[int4] = m["device_bytes"]["pools"]["params"]
            assert m["int4_weights"] is int4
    assert sizes[True] < 0.45 * sizes[False], sizes
    # the matmul planes themselves (the part int4 packs) shrink ~8x
    q4 = DecodeEngine(CFG, PARAMS, 2, prefill_chunk=4, num_blocks=NB,
                      int4_weights=True)
    full = DecodeEngine(CFG, PARAMS, 2, prefill_chunk=4, num_blocks=NB)
    packed = sum(int(np.prod(q4._blocks[wk].shape))
                 for wk, _ in QUANT_DECODE_PAIRS)
    plain = sum(int(np.prod(full._blocks[wk].shape))
                * full._blocks[wk].dtype.itemsize
                for wk, _ in QUANT_DECODE_PAIRS)
    assert packed * 8 == plain


# ----------------------------------------------------------- chaos soak
@pytest.mark.slow
def test_chaos_soak_with_int4_armed():
    """The resilience chaos soak rides with int4 weights armed: every
    injection point firing at low probability over a mixed workload,
    every request completes, the streams stay bit-identical to an
    undisturbed int4 server (the packed pool makes regeneration
    deterministic exactly like full precision), and the block refcount
    audit stays clean."""
    rs = np.random.RandomState(11)
    cases = [dict(p=_prompt(rs, rs.randint(5, 14)),
                  max_tokens=int(rs.randint(4, 8)))
             for _ in range(12)]
    outs = {}
    for chaos in ("", "all:0.02,seed:3,hang_ms:50"):
        with InferenceServer(CFG, PARAMS, slots=2, queue=16,
                             prefill_chunk=4, num_blocks=NB,
                             int4_weights=True, spec_mode="ngram",
                             spec_len=2, chaos=chaos,
                             max_restarts=50) as srv:
            hs = [srv.submit(c["p"], max_tokens=c["max_tokens"])
                  for c in cases]
            outs[chaos] = [srv.result(h, timeout=600) for h in hs]
            eng = srv._engine
            eng.manager.check_consistency(
                srv._prefix.trie_refs() if srv._prefix is not None else 0)
    for a, b in zip(outs[""], outs["all:0.02,seed:3,hang_ms:50"]):
        assert a.status == "ok" and b.status == "ok"
        np.testing.assert_array_equal(a.tokens, b.tokens)
