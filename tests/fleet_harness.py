"""Shared process-fleet test harness (round 17).

Extracted from tests/test_multihost.py so the serving-fleet tests
(tests/test_fleet.py) and the multihost training tests drive worker
processes through ONE copy of the flake-hardened spawn logic instead of
a copy-paste fork: free-port allocation, continuous pipe-drain readers
(a worker whose crash logs overflow the OS pipe buffer must not block
in write() and turn a fast failure into a full-timeout kill), the
peer-kill grace window, and the infrastructure-signature retry gate.
"""

import os
import socket
import subprocess
import sys
import threading
import time


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# gloo/coordination-service INFRASTRUCTURE failure signatures. Under
# full-suite CPU load a worker can stall past the coordination
# service's heartbeat/barrier windows while its peer is mid-compile —
# the run dies with one of these even though nothing is wrong with the
# code under test (observed flaking tier-1 since round 15; reproduced
# in the round-18 baseline). NOTE these can also appear as SECONDARY
# symptoms when a peer dies of a genuine python failure (the survivor
# then sees connection-reset/heartbeat noise), so retry eligibility
# additionally requires that no worker printed a python traceback free
# of these signs — see genuine_failure below.
INFRA_SIGNS = ("heartbeat timeout", "Shutdown barrier", "Barrier failed",
               "DEADLINE_EXCEEDED", "coordination service",
               "Connection refused", "failed to connect",
               "Timed out waiting for",
               # gloo's TCP transport aborting on a torn message (a
               # SIGABRT with 'op.preamble.length <= op.nbytes' —
               # observed once under full-suite load, round 18)
               "gloo::EnforceNotMet", "enforce fail at",
               "Connection reset by peer",
               # the survivor's view of a peer felled by any of the
               # above: its own collective dies mid-message (secondary
               # symptom — must not defeat the retry OR count as a
               # genuine python failure)
               "Connection closed by peer", "Gloo all-reduce failed")

# Once any worker has exited nonzero its peers can only hang (blocked in
# a collective / the coordination barrier waiting for the dead rank,
# until some heartbeat window expires minutes later) — give them this
# long to surface their own output, then kill them.
PEER_GRACE_S = 15.0


def genuine_failure(outs):
    """True when some worker output shows a python failure of its own
    (traceback with no infrastructure signature in the whole output) —
    e.g. an AssertionError or the pre-existing shard_map AttributeError.
    Such runs must FAIL, not retry: the peer's secondary heartbeat /
    connection-reset noise does not make them infrastructure flakes."""
    return any("Traceback (most recent call last)" in o
               and not any(sign in o for sign in INFRA_SIGNS)
               for o in outs)


def run_workers(script, ranks, tmp_path, extra=None, timeout=240,
                attempts=3, env_extra=None):
    """Spawn one ``script`` process per rank and gate the test on ALL
    of them exiting 0. Spawns are staggered (rank 0 binds the
    coordinator before peers dial); a hung run is killed at
    ``timeout``; peers of a crashed worker are killed after
    PEER_GRACE_S instead of being left to ride out heartbeat windows;
    and a run that died of rendezvous / heartbeat INFRASTRUCTURE
    symptoms (INFRA_SIGNS — the load-flake this helper exists for, not
    test logic) is retried on a fresh port (up to ``attempts`` total
    tries) before failing for real. A run where any worker hit a
    genuine python failure is never retried.

    Each worker gets argv ``[script, rank, port, tmp_path] + extra``
    and a clean environment: ambient PYTHONPATH stripped, repo root
    substituted (matches what serve/fleet.py does for serving workers).
    """
    env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    # arm the lock-order watchdog (analysis/concurrency.py) in every
    # spawned worker: both fleet suites then exercise the runtime
    # inversion detector for free — a real inversion in the serving
    # stack fails the worker loudly instead of deadlocking at timeout
    env.setdefault("CXN_LOCK_WATCH", "1")
    if env_extra:
        env.update(env_extra)
    for attempt in range(attempts):
        port = str(free_port())
        procs = []
        for r in ranks:
            procs.append(subprocess.Popen(
                [sys.executable, script, str(r), port, str(tmp_path)]
                + list(extra or ()),
                env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT))
            time.sleep(0.2)
        timed_out = False
        deadline = time.time() + timeout
        grace_deadline = None
        # reader threads drain every pipe CONTINUOUSLY: a worker whose
        # failure logs exceed the OS pipe buffer must not block in
        # write() and turn a fast crash into a full-timeout kill
        bufs = [[] for _ in procs]
        readers = [threading.Thread(
            target=lambda p=p, b=b: b.append(p.stdout.read()),
            daemon=True) for p, b in zip(procs, bufs)]
        for t in readers:
            t.start()
        try:
            while any(p.poll() is None for p in procs):
                now = time.time()
                if grace_deadline is None and any(
                        p.poll() not in (None, 0) for p in procs):
                    grace_deadline = now + PEER_GRACE_S
                if now >= deadline or (grace_deadline is not None
                                       and now >= grace_deadline):
                    timed_out = now >= deadline
                    for p in procs:
                        if p.poll() is None:
                            p.kill()
                    break
                time.sleep(0.25)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
                p.wait()
            for t in readers:
                t.join(timeout=10)
        outs = [(b[0] if b else b"").decode(errors="replace")
                for b in bufs]
        if all(p.returncode == 0 for p in procs):
            return outs
        signs = any(sign in o for o in outs for sign in INFRA_SIGNS)
        infra = (signs or timed_out) and not genuine_failure(outs)
        # a bare timeout with NO infra output could just as well be a
        # genuine cross-process deadlock in the code under test — give
        # it ONE retry, not the whole attempt budget (which would burn
        # attempts x timeout of tier-1 wall clock before failing)
        if infra and (signs or attempt == 0) and attempt + 1 < attempts:
            continue                    # fresh port, one more try
        # every worker's view, not just the first dead one: the first
        # nonzero exit is often a SECONDARY casualty (grace-killed, or
        # felled by its peer's death mid-collective)
        assert False, "worker(s) failed:\n%s" % "\n".join(
            "---- rank%s rc=%s ----\n%s" % (r, p.returncode, o[-4000:])
            for r, p, o in zip(ranks, procs, outs))
    return outs
