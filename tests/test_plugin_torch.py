"""Torch adapter plugin layer (the caffe-adapter analogue, SURVEY.md §2.2):
an external framework's op as a production layer and as a pairtest oracle."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from cxxnet_tpu import Net  # noqa: E402
from cxxnet_tpu.io.data import DataBatch  # noqa: E402
from cxxnet_tpu.utils.config import tokenize  # noqa: E402
from cxxnet_tpu.graph import LayerSpec  # noqa: E402
from cxxnet_tpu.layers import ApplyContext, create_layer  # noqa: E402


def make_layer(module, extra=(), in_shape=(3, 8, 8)):
    spec = LayerSpec("torch", "t0", [0], [1])
    lay = create_layer(spec, [("module", module)] + list(extra))
    out_shape = lay.infer_shapes([in_shape])
    params = lay.init_params(jax.random.PRNGKey(0), [in_shape])
    return lay, params, out_shape[0]


def test_forward_matches_torch_conv():
    lay, params, out_shape = make_layer("Conv2d(3, 6, 3, padding=1)")
    assert out_shape == (6, 8, 8)
    rs = np.random.RandomState(0)
    x = rs.randn(4, 8, 8, 3).astype(np.float32)          # NHWC runtime node
    ctx = ApplyContext(train=False, rng=None)
    (y,) = lay.apply(params, [jnp.asarray(x)], ctx)
    # oracle: same module, same blobs, NCHW
    w = torch.from_numpy(np.asarray(params["blob0"]))
    b = torch.from_numpy(np.asarray(params["blob1"]))
    ref = torch.nn.functional.conv2d(
        torch.from_numpy(x.transpose(0, 3, 1, 2)), w, b, padding=1)
    np.testing.assert_allclose(np.asarray(y).transpose(0, 3, 1, 2),
                               ref.numpy(), rtol=1e-5, atol=1e-5)


def test_gradients_match_torch_autograd():
    lay, params, _ = make_layer("Linear(12, 5)", in_shape=(1, 1, 12))
    rs = np.random.RandomState(1)
    x = jnp.asarray(rs.randn(4, 1, 1, 12).astype(np.float32))
    ctx = ApplyContext(train=True, rng=jax.random.PRNGKey(0))

    def loss(p, x):
        (y,) = lay.apply(p, [x], ctx)
        return jnp.sum(y ** 2)

    gp, gx = jax.grad(loss, argnums=(0, 1))(params, x)
    # oracle
    xt = torch.from_numpy(np.asarray(x).reshape(4, 12)).requires_grad_(True)
    wt = torch.from_numpy(np.asarray(params["blob0"])).requires_grad_(True)
    bt = torch.from_numpy(np.asarray(params["blob1"])).requires_grad_(True)
    (torch.nn.functional.linear(xt, wt, bt) ** 2).sum().backward()
    np.testing.assert_allclose(np.asarray(gp["blob0"]), wt.grad.numpy(),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gp["blob1"]), bt.grad.numpy(),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gx).reshape(4, 12), xt.grad.numpy(),
                               rtol=1e-4, atol=1e-5)


def test_pairtest_fullc_vs_torch(capfd):
    """The torch Linear is the oracle slave of the native fullc: one shared
    parameter set (param_names renames blobs), any divergence would print a
    PairTest report."""
    cfg = """
netconfig=start
layer[0->1] = flatten
layer[1->2] = pairtest-fullc-torch:pt
  nhidden = 16
  init_sigma = 0.05
  slave:module = "Linear(48, 16)"
  slave:param_names = wmat,bias
layer[2->3] = relu
layer[3->4] = fullc:fc2
  nhidden = 4
  init_sigma = 0.1
layer[4->4] = softmax
netconfig=end
input_shape = 3,4,4
batch_size = 8
dev = cpu
eta = 0.1
metric = error
"""
    net = Net(tokenize(cfg))
    net.init_model()
    rs = np.random.RandomState(0)
    for _ in range(2):
        x = rs.randn(8, 3, 4, 4).astype(np.float32)
        y = rs.randint(0, 4, (8, 1)).astype(np.float32)
        net.update(DataBatch(x, y))
    jax.effects_barrier()
    assert "PairTest" not in capfd.readouterr().out


def test_pairtest_conv_vs_torch(capfd):
    """Native conv (HWIO weights) against torch Conv2d via hwio=1 exposure."""
    cfg = """
netconfig=start
layer[0->1] = pairtest-conv-torch:pt
  kernel_size = 3
  pad = 1
  nchannel = 8
  init_sigma = 0.05
  slave:module = "Conv2d(2, 8, 3, padding=1)"
  slave:param_names = wmat,bias
  slave:hwio = 1
layer[1->2] = flatten
layer[2->3] = fullc:fc
  nhidden = 4
  init_sigma = 0.1
layer[3->3] = softmax
netconfig=end
input_shape = 2,8,8
batch_size = 8
dev = cpu
eta = 0.1
metric = error
"""
    net = Net(tokenize(cfg))
    net.init_model()
    rs = np.random.RandomState(0)
    x = rs.randn(8, 2, 8, 8).astype(np.float32)
    y = rs.randint(0, 4, (8, 1)).astype(np.float32)
    net.update(DataBatch(x, y))
    jax.effects_barrier()
    assert "PairTest" not in capfd.readouterr().out


def test_torch_layer_trains_in_net():
    """A torch module as a production layer: the whole net still trains
    (grads flow through the callback's custom_vjp)."""
    cfg = """
netconfig=start
layer[0->1] = torch:tc1
  module = "Sequential(Conv2d(1, 4, 3, padding=1), ReLU())"
layer[1->2] = flatten
layer[2->3] = fullc:fc
  nhidden = 2
  init_sigma = 0.1
layer[3->3] = softmax
netconfig=end
input_shape = 1,6,6
batch_size = 16
dev = cpu
eta = 0.5
metric = error
"""
    net = Net(tokenize(cfg))
    net.init_model()
    rs = np.random.RandomState(0)
    x = rs.randn(16, 1, 6, 6).astype(np.float32)
    y = (x.mean(axis=(1, 2, 3)) > 0).astype(np.float32).reshape(16, 1)
    losses = []
    for _ in range(15):
        net.update(DataBatch(x, y))
        losses.append(float(net._last_loss))
    assert losses[-1] < 0.5 * losses[0], \
        "loss did not decrease: %s" % losses


def test_module_expr_errors():
    from cxxnet_tpu.utils.config import ConfigError
    with pytest.raises(ConfigError):
        make_layer("not_a_module(")
    with pytest.raises(ConfigError):
        make_layer("Linear(3, 4)", extra=[("param_names", "only_one")],
                   in_shape=(1, 1, 3))
