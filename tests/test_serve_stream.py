"""Streaming online-softmax fused paged attention.

The load-bearing invariants of the STREAMING formulation
(ops/pallas_kernels.py:_paged_attn_stream_kernel, the
``paged_attention_formulation`` gate, the engine's formulation
threading):

1. **gate selection** — geometry inside the resident VMEM budget keeps
   the round-16 resident kernel; a row image past the budget (here: a
   clamped ``_PAGED_RESIDENT_VMEM``, the CI stand-in for a
   production-length row blowing the real 12 MiB gate) resolves
   ``"streaming"`` instead of falling back to gather. The off-switches
   (param / env) still win.
2. **numerics under the shared contract** — streaming-vs-gather
   agreement asserts through ``assert_fused_allclose(...,
   formulation="streaming")``: the online-softmax reassociation band
   for f32, the bf16 band on bf16 pools — never ad-hoc tolerances.
   Garbage (id 0) table entries stay masked. Served TOKENS are pinned
   bit-identical to the gather path and the solo oracle (the band is
   orders of magnitude below any argmax margin).
3. **int8-KV composes** — the scale-plane operands ride through the
   streaming grid exactly as through the resident one; a streaming
   int8 engine is token-identical to the gather int8 engine.
4. **compiled-program hygiene** — the streaming/resident choice is
   engine-construction state, NOT signature state (PR 10 idiom): one
   compiled tick signature across mixed row lengths, and a streaming
   engine's RecompileGuard signatures equal a resident engine's.
5. **fallback observability** — a fused request the backend cannot
   serve logs its reason once and counts it in
   ``cxn_fused_fallback_total{reason=}``.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import cxxnet_tpu.ops.pallas_kernels as pk
from cxxnet_tpu.models.gpt import GPTConfig, gpt_decode, gpt_init
from cxxnet_tpu.serve import (DecodeEngine, InferenceServer,
                              assert_fused_allclose, fused_attn_tolerance)
from cxxnet_tpu.serve.engine import (_attn_cached_rows, _attn_verify,
                                     _gather_row, _gather_rows)

CFG = GPTConfig(vocab_size=32, seq_len=32, n_layer=2, n_head=2, feat=16,
                n_microbatch=1)
PARAMS = gpt_init(jax.random.PRNGKey(5), CFG)
HD = CFG.feat // CFG.n_head


@pytest.fixture(autouse=True)
def interpret(monkeypatch):
    monkeypatch.setattr(pk, "_INTERPRET", True)


def _force_streaming(monkeypatch, block_size=4):
    """Clamp the resident VMEM budget to exactly one f32 block image:
    every full row here overflows it (streaming selected), while a
    single block of any served dtype still fits (the streaming gate
    passes)."""
    gate = pk._paged_row_vmem(CFG.n_head, 1, block_size, HD, 4)
    monkeypatch.setattr(pk, "_PAGED_RESIDENT_VMEM", gate)


def _prompt(rs, n):
    return rs.randint(0, CFG.vocab_size, (n,)).astype(np.int32)


def _ref(prompt, max_new, **kw):
    seed = kw.pop("seed", 0)
    t = kw.get("temperature", 0.0)
    rng = jax.random.PRNGKey(seed) if t > 0 else None
    return np.asarray(gpt_decode(PARAMS, prompt[None], max_new, CFG,
                                 rng=rng, **kw))[0]


# ------------------------------------------------------ gate selection
def test_formulation_crossover(monkeypatch):
    """Same geometry, two budgets: the stock gate resolves resident,
    the clamped gate resolves streaming — and both count as fused."""
    bpr = CFG.seq_len // 4
    assert pk.paged_attention_formulation(CFG.n_head, bpr, 4, HD,
                                          4) == "resident"
    _force_streaming(monkeypatch)
    assert pk.paged_attention_formulation(CFG.n_head, bpr, 4, HD,
                                          4) == "streaming"
    eng = DecodeEngine(CFG, PARAMS, slots=2, prefill_chunk=4,
                       num_blocks=30, fused_attn=True)
    assert eng.fused_attn and eng.fused_formulation == "streaming"
    eng.close()


def test_streaming_respects_off_switches(monkeypatch):
    _force_streaming(monkeypatch)
    eng = DecodeEngine(CFG, PARAMS, slots=2, prefill_chunk=4,
                       num_blocks=30, fused_attn=False)
    assert eng.fused_attn is False and eng.fused_formulation == ""
    eng.close()
    monkeypatch.setenv("CXN_FUSED_ATTN", "0")
    assert pk.paged_attention_formulation(CFG.n_head, 12, 4, HD, 4) == ""


def test_streaming_tolerance_band_is_the_contract():
    """The streaming branch of the shared contract is a band, not
    exact (online-softmax reassociation), and the default resident
    branch stays exact here — the contract test_serve_fused pins."""
    tol = fused_attn_tolerance(formulation="streaming")
    assert tol["rtol"] > 0.0 and tol["atol"] > 0.0
    assert fused_attn_tolerance() == {"rtol": 0.0, "atol": 0.0}


# ----------------------------------------------------- kernel numerics
def test_kernel_streaming_vs_gather_reference():
    """paged_attention(streaming=True) against the gather reference,
    tick AND verify shapes, f32 and bf16, garbage (id 0) table entries
    — inside the streaming band of the shared contract."""
    rs = np.random.RandomState(0)
    L, NB, H, bs = 2, 20, CFG.n_head, 4
    b, bpr = 3, 6
    for dtype in (jnp.float32, jnp.bfloat16):
        pool_k = jnp.asarray(rs.randn(L, NB, H, bs, HD), dtype)
        pool_v = jnp.asarray(rs.randn(L, NB, H, bs, HD), dtype)
        table = np.zeros((b, bpr), np.int32)
        table[0, :3] = [5, 9, 2]            # rest: garbage block 0
        table[1, :5] = [7, 11, 1, 3, 8]
        table[2, :2] = [4, 6]
        table = jnp.asarray(table)
        pos = jnp.asarray([9, 17, 6], jnp.int32)
        q = jnp.asarray(rs.randn(b, 1, H, HD), dtype)

        @jax.jit
        def gather_tick(q, pk_, pv_, table, pos):
            ck = _gather_rows(pk_[1], table, H, bs)
            cv = _gather_rows(pv_[1], table, H, bs)
            return _attn_cached_rows(q, ck, cv, pos)

        @jax.jit
        def stream_tick(q, pk_, pv_, table, pos):
            return pk.paged_attention(q, pk_, pv_, table, pos, 1, bs,
                                      streaming=True)

        assert_fused_allclose(
            stream_tick(q, pool_k, pool_v, table, pos),
            gather_tick(q, pool_k, pool_v, table, pos),
            "tick %s" % dtype.__name__, formulation="streaming")

        R = 4
        qv = jnp.asarray(rs.randn(1, R, H, HD), dtype)
        vpos = jnp.asarray(9, jnp.int32)

        @jax.jit
        def gather_verify(q, pk_, pv_, table, pos):
            ck = _gather_row(pk_[0], table[0], H, bs)
            cv = _gather_row(pv_[0], table[0], H, bs)
            return _attn_verify(q, ck, cv, pos)

        @jax.jit
        def stream_verify(q, pk_, pv_, table, pos):
            return pk.paged_attention(q, pk_, pv_, table[:1],
                                      jnp.reshape(pos, (1,)), 0, bs,
                                      streaming=True)

        assert_fused_allclose(
            stream_verify(qv, pool_k, pool_v, table, vpos),
            gather_verify(qv, pool_k, pool_v, table, vpos),
            "verify %s" % dtype.__name__, formulation="streaming")


# ------------------------------------------------- served-token identity
def test_streaming_vs_gather_vs_oracle_mixed_workload(monkeypatch):
    """The tentpole differential, streaming edition: mixed lengths,
    sampling, shared prefixes served with the STREAMING kernel produce
    tokens identical to the solo oracle. (gather == the same oracle
    over mixed traffic is test_serve.py's pin, so streaming == gather
    follows.)"""
    _force_streaming(monkeypatch)
    rs = np.random.RandomState(0)
    shared = _prompt(rs, 12)
    cases = [
        dict(p=_prompt(rs, 3), max_tokens=5),
        dict(p=_prompt(rs, 9), max_tokens=5, temperature=0.8, top_k=5,
             top_p=0.9, seed=7),
        dict(p=np.concatenate([shared, _prompt(rs, 5)]), max_tokens=5),
    ]
    with InferenceServer(CFG, PARAMS, slots=2, queue=16,
                         prefill_chunk=4, fused_attn=True) as srv:
        m = srv.metrics()["paged"]
        assert m["fused_attn"] is True
        assert m["fused_formulation"] == "streaming"
        hs = [srv.submit(c["p"], **{k: v for k, v in c.items()
                                    if k != "p"}) for c in cases]
        outs = [srv.result(h, timeout=300) for h in hs]
    assert all(r.status == "ok" for r in outs)
    for c, rf in zip(cases, outs):
        kw = {k: v for k, v in c.items() if k not in ("p", "max_tokens")}
        ref = _ref(c["p"], c["max_tokens"], **kw)
        np.testing.assert_array_equal(rf.tokens, ref)


def test_streaming_speculative_identity(monkeypatch):
    """The streaming VERIFY program (R > 1 rows through the online-
    softmax grid) stays token-identical to the solo oracle."""
    _force_streaming(monkeypatch)
    rs = np.random.RandomState(3)
    base = _prompt(rs, 6)
    prompt = np.concatenate([base, base, base])
    with InferenceServer(CFG, PARAMS, slots=2, queue=8, prefill_chunk=4,
                         spec_mode="ngram", spec_len=3,
                         fused_attn=True) as srv:
        assert srv.metrics()["paged"]["fused_formulation"] == "streaming"
        res = srv.result(srv.submit(prompt, max_tokens=8), timeout=300)
        m = srv.metrics()
    assert res.status == "ok"
    np.testing.assert_array_equal(res.tokens, _ref(prompt, 8))
    assert m["spec_forwards"] >= 1


def test_streaming_int8_kv_identity(monkeypatch):
    """int8-KV through the streaming grid: the scale planes ride the
    same block walk, and the streaming int8 server is token-identical
    to the gather int8 server (same quantized pool, so the only delta
    is the attention read — inside the streaming band, below any
    greedy margin)."""
    _force_streaming(monkeypatch)
    rs = np.random.RandomState(9)
    prompts = [_prompt(rs, n) for n in (5, 11)]
    outs = {}
    for fused in (True, False):
        with InferenceServer(CFG, PARAMS, slots=2, queue=8,
                             prefill_chunk=4, kv_dtype="int8",
                             fused_attn=fused) as srv:
            m = srv.metrics()["paged"]
            assert m["kv_dtype"] == "int8"
            assert m["fused_formulation"] == ("streaming" if fused
                                              else "")
            hs = [srv.submit(p, max_tokens=5) for p in prompts]
            outs[fused] = [srv.result(h, timeout=300).tokens for h in hs]
    for tf, tg in zip(outs[True], outs[False]):
        np.testing.assert_array_equal(tf, tg)


# ------------------------------------------- compiled-program hygiene
def test_one_signature_streaming_across_mixed_lengths(monkeypatch):
    """Mixed-length traffic through a strict RecompileGuard with the
    STREAMING kernel armed: one compiled signature per program — row
    length is masked data, never a recompile trigger, exactly as on
    the resident and gather paths."""
    _force_streaming(monkeypatch)
    rs = np.random.RandomState(9)
    with InferenceServer(CFG, PARAMS, slots=3, queue=64, prefill_chunk=4,
                         recompile_limit=1, recompile_strict=True,
                         spec_mode="ngram", spec_len=2,
                         fused_attn=True) as srv:
        hs = [srv.submit(_prompt(rs, 1 + (i * 7) % 20), max_tokens=3)
              for i in range(8)]
        assert all(srv.result(h, timeout=300).status == "ok"
                   for h in hs)
        eng = srv._engine
        assert eng.fused_formulation == "streaming"
        assert len(eng.prefill_signatures) == 1, eng.prefill_signatures
        assert len(eng.tick_signatures) == 1, eng.tick_signatures
        assert len(eng.verify_signatures) <= 1


def test_guard_signatures_do_not_carry_formulation(monkeypatch):
    """The resident/streaming choice is construction state: a
    streaming engine and a resident engine over the same traffic count
    IDENTICAL RecompileGuard signatures, and no signature string
    carries the formulation (PR 10's flag-free idiom, extended)."""
    rs = np.random.RandomState(2)
    prompt = _prompt(rs, 6)
    sigs = {}
    for streaming in (True, False):
        if streaming:
            monkeypatch.setattr(
                pk, "_PAGED_RESIDENT_VMEM",
                pk._paged_row_vmem(CFG.n_head, 1, 4, HD, 4))
        else:
            monkeypatch.setattr(pk, "_PAGED_RESIDENT_VMEM",
                                12 * 1024 * 1024)
        with InferenceServer(CFG, PARAMS, slots=2, queue=4,
                             prefill_chunk=4, recompile_limit=2,
                             spec_mode="ngram", spec_len=2,
                             fused_attn=True) as srv:
            assert srv.metrics()["paged"]["fused_formulation"] == \
                ("streaming" if streaming else "resident")
            srv.result(srv.submit(np.concatenate([prompt, prompt]),
                                  max_tokens=4), timeout=300)
            eng = srv._engine
            sigs[streaming] = (eng.prefill_signatures,
                               eng.tick_signatures,
                               eng.verify_signatures)
    assert sigs[True] == sigs[False], sigs
    for group in sigs[True]:
        for s in group:
            assert "stream" not in s and "resident" not in s, s


def test_streaming_audit_fully_aliased_and_clip_folded(monkeypatch):
    """cxn-lint pass 2 on the STREAMING engine: pool donation aliasing
    end to end and every index clip folded (CXN208), exactly like the
    resident programs."""
    from cxxnet_tpu.analysis import audit_serve_engine
    _force_streaming(monkeypatch)
    eng = DecodeEngine(CFG, PARAMS, slots=2, prefill_chunk=4,
                       num_blocks=30, spec_len=2, abstract=True,
                       fused_attn=True)
    assert eng.fused_formulation == "streaming"
    report, infos = audit_serve_engine(eng, donate=True)
    assert report.ok(), report.format()
    for info in infos:
        if info["label"] in ("serve_verify_chunk", "serve_tick"):
            assert info["donated"] == 2 and info["aliased"] == 2, info
            assert info["entry_clamps"] == 0, info


# ------------------------------------------------ fallback observability
def test_fallback_reason_counted_once(monkeypatch):
    """An unsupported fused request resolves gather, logs its reason
    through the profiler ONCE per process, and counts every resolution
    in cxn_fused_fallback_total{reason=}."""
    import cxxnet_tpu.serve.engine as eng_mod
    monkeypatch.setattr(pk, "_INTERPRET", False)    # CPU: backend gate
    monkeypatch.setattr(eng_mod, "_FALLBACK_LOGGED", set())
    logged = []
    from cxxnet_tpu.utils import profiler
    monkeypatch.setattr(profiler, "log",
                        lambda msg, *a, **k: logged.append(msg))
    with InferenceServer(CFG, PARAMS, slots=2, queue=4,
                         prefill_chunk=4, fused_attn=True) as srv:
        assert srv.metrics()["paged"]["fused_attn"] is False
        snap = srv.registry.snapshot()
    key = 'cxn_fused_fallback_total{reason="backend"}'
    assert snap.get(key) == 1, snap
    hits = [m for m in logged if "fused paged attention unavailable" in m]
    assert len(hits) == 1 and "reason=backend" in hits[0]
    # second engine, same process: counted again, logged never again
    with InferenceServer(CFG, PARAMS, slots=2, queue=4,
                         prefill_chunk=4, fused_attn=True) as srv:
        snap = srv.registry.snapshot()
    assert snap.get(key) == 1         # per-server registry: one build
    hits = [m for m in logged if "fused paged attention unavailable" in m]
    assert len(hits) == 1
