"""Switch-MoE op + layer: routing math, expert parallelism, training."""

import jax
import jax.numpy as jnp
import numpy as np

from cxxnet_tpu import Net
from cxxnet_tpu.io.data import DataBatch
from cxxnet_tpu.models import transformer_config
from cxxnet_tpu.ops.moe import switch_moe
from cxxnet_tpu.parallel.mesh import make_mesh
from cxxnet_tpu.utils.config import tokenize


def _weights(rs, e=4, d=8, h=16):
    return (jnp.asarray(rs.randn(d, e).astype(np.float32)),
            jnp.asarray(rs.randn(e, d, h).astype(np.float32) * 0.1),
            jnp.asarray(rs.randn(e, h, d).astype(np.float32) * 0.1))


def test_switch_moe_matches_dense_per_token():
    """With ample capacity, each token's output must equal gate_prob *
    FFN_{argmax expert}(token) computed densely."""
    rs = np.random.RandomState(0)
    wg, wu, wd = _weights(rs)
    x = jnp.asarray(rs.randn(32, 8).astype(np.float32))
    out, aux = switch_moe(x, wg, wu, wd, capacity_factor=8.0)

    probs = np.asarray(jax.nn.softmax(x @ wg, axis=-1))
    idx = probs.argmax(-1)
    for t in range(32):
        e = idx[t]
        hdn = np.maximum(np.asarray(x[t]) @ np.asarray(wu[e]), 0)
        ref = probs[t, e] * (hdn @ np.asarray(wd[e]))
        np.testing.assert_allclose(np.asarray(out[t]), ref, rtol=1e-4,
                                   atol=1e-5)
    assert float(aux) >= 1.0 - 1e-5    # E * sum f_e p_e >= 1 at optimum


def test_capacity_drops_overflow_tokens():
    rs = np.random.RandomState(1)
    wg, wu, wd = _weights(rs, e=2)
    # route every token to the same expert: huge gate column
    wg = wg.at[:, 0].set(100.0 * jnp.sign(wg[:, 0]).sum() + 100.0)
    x = jnp.abs(jnp.asarray(rs.randn(16, 8).astype(np.float32)))
    out, _ = switch_moe(x, wg, wu, wd, capacity_factor=0.25)  # cap = 2
    norms = np.linalg.norm(np.asarray(out), axis=1)
    assert (norms[:2] > 0).all()          # first two tokens served
    assert (norms[2:] == 0).all()         # overflow dropped


def test_expert_parallel_matches_single_device():
    rs = np.random.RandomState(2)
    wg, wu, wd = _weights(rs)
    x = jnp.asarray(rs.randn(64, 8).astype(np.float32))
    ref, _ = switch_moe(x, wg, wu, wd)

    mesh = make_mesh("cpu:0-7", model_parallel=4)
    from jax.sharding import NamedSharding, PartitionSpec as P
    wu_s = jax.device_put(wu, NamedSharding(mesh, P("model")))
    wd_s = jax.device_put(wd, NamedSharding(mesh, P("model")))
    out, _ = jax.jit(switch_moe)(x, wg, wu_s, wd_s)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_moe_transformer_trains():
    cfg = transformer_config(seq_len=16, vocab_size=16, feat=16, nhead=2,
                             nblock=1, num_classes=4, batch_size=16,
                             dev="cpu:0-7", model_parallel=4, moe_experts=4)
    net = Net(tokenize(cfg))
    net.init_model()
    # expert dim actually sharded over the model axis
    assert net.params["moe0"]["w_up"].sharding.spec[0] == "model"
    rs = np.random.RandomState(0)
    before = [np.asarray(t).copy() for t in jax.tree.leaves(net.params)]
    for i in range(3):
        ids = rs.randint(0, 16, (16, 1, 1, 16)).astype(np.float32)
        lab = rs.randint(0, 4, (16, 1)).astype(np.float32)
        net.update(DataBatch(ids, lab))
    after = [np.asarray(t) for t in jax.tree.leaves(net.params)]
    assert any(np.abs(a - b).sum() > 0 for a, b in zip(after, before))


def test_sort_dispatch_matches_dense():
    """The sort-based sparse dispatch assigns queue positions in token
    order (stable argsort), so outputs — including which overflow tokens
    drop — must equal the dense one-hot formulation exactly."""
    rs = np.random.RandomState(3)
    for e, cap in ((4, 8.0), (4, 0.5), (8, 0.25)):
        wg, wu, wd = _weights(rs, e=e)
        x = jnp.asarray(rs.randn(48, 8).astype(np.float32))
        dense, aux_d = switch_moe(x, wg, wu, wd, capacity_factor=cap,
                                  dispatch="dense")
        sort, aux_s = switch_moe(x, wg, wu, wd, capacity_factor=cap,
                                 dispatch="sort")
        np.testing.assert_allclose(np.asarray(sort), np.asarray(dense),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(float(aux_s), float(aux_d), rtol=1e-5)


def test_sort_dispatch_gradients_match_dense():
    rs = np.random.RandomState(4)
    wg, wu, wd = _weights(rs)
    x = jnp.asarray(rs.randn(32, 8).astype(np.float32))

    def loss(disp, xx, g, u, dn):
        out, aux = switch_moe(xx, g, u, dn, capacity_factor=0.75,
                              dispatch=disp)
        return jnp.sum(out * out) + 0.01 * aux

    gd = jax.grad(lambda *a: loss("dense", *a), argnums=(0, 1, 2, 3))(
        x, wg, wu, wd)
    gs = jax.grad(lambda *a: loss("sort", *a), argnums=(0, 1, 2, 3))(
        x, wg, wu, wd)
    for a, b in zip(gs, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_alltoall_matches_single_device():
    """Explicit expert-parallel all-to-all dispatch over a real expert
    mesh axis == the single-shard computation, when capacity is ample
    (grouped capacity semantics coincide with global only without
    drops)."""
    from cxxnet_tpu.ops.moe import switch_moe_alltoall
    from jax.sharding import NamedSharding, PartitionSpec as P
    import functools

    rs = np.random.RandomState(5)
    e, d_model = 8, 8
    wg, wu, wd = _weights(rs, e=e)
    x = jnp.asarray(rs.randn(64, d_model).astype(np.float32))
    ref, aux_ref = switch_moe(x, wg, wu, wd, capacity_factor=float(e))

    mesh = make_mesh("cpu:0-7", expert_parallel=4)
    body = functools.partial(switch_moe_alltoall, axis_name="expert",
                             capacity_factor=float(e))
    tok = P(("data", "expert"), None)
    out, aux = jax.jit(jax.shard_map(
        body, mesh=mesh,
        in_specs=(tok, P(None, None), P("expert", None, None),
                  P("expert", None, None)),
        out_specs=(tok, P()), check_vma=False))(x, wg, wu, wd)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_alltoall_grouped_capacity_drops():
    """With expert parallelism the capacity bound applies per (source
    shard, expert) group. Force every token to expert 0: each of the 4
    shards keeps ceil(S_local/E * cf) tokens, the rest drop to zero."""
    from cxxnet_tpu.ops.moe import switch_moe_alltoall
    from jax.sharding import PartitionSpec as P
    import functools, math

    rs = np.random.RandomState(6)
    e = 4
    wg, wu, wd = _weights(rs, e=e)
    wg = jnp.zeros_like(wg).at[:, 0].set(100.0)
    x = jnp.abs(jnp.asarray(rs.randn(32, 8).astype(np.float32)))

    mesh = make_mesh("cpu:0-7", expert_parallel=4)
    nd = mesh.shape["data"]
    s_local = 32 // (nd * 4)                # data=2 x expert=4 -> 4/shard
    cap = max(1, math.ceil(s_local / e * 1.0))
    body = functools.partial(switch_moe_alltoall, axis_name="expert",
                             capacity_factor=1.0)
    tok = P(("data", "expert"), None)
    out, _ = jax.jit(jax.shard_map(
        body, mesh=mesh,
        in_specs=(tok, P(None, None), P("expert", None, None),
                  P("expert", None, None)),
        out_specs=(tok, P()), check_vma=False))(x, wg, wu, wd)
    norms = np.linalg.norm(np.asarray(out), axis=1).reshape(nd * 4, s_local)
    # per shard: first `cap` tokens served, the rest dropped
    assert (norms[:, :cap] > 0).all(), norms
    if s_local > cap:
        assert (norms[:, cap:] == 0).all(), norms


def test_moe_transformer_expert_axis_trains():
    """End-to-end through Net: expert_parallel=4 gives the weights a real
    'expert' mesh axis and routes through the all-to-all dispatch."""
    cfg = transformer_config(seq_len=16, vocab_size=16, feat=16, nhead=2,
                             nblock=1, num_classes=4, batch_size=16,
                             dev="cpu:0-7", moe_experts=4)
    cfg += "\nexpert_parallel = 4\n"
    net = Net(tokenize(cfg))
    net.init_model()
    assert net.params["moe0"]["w_up"].sharding.spec[0] == "expert"
    rs = np.random.RandomState(0)
    before = [np.asarray(t).copy() for t in jax.tree.leaves(net.params)]
    for i in range(3):
        ids = rs.randint(0, 16, (16, 1, 1, 16)).astype(np.float32)
        lab = rs.randint(0, 4, (16, 1)).astype(np.float32)
        net.update(DataBatch(ids, lab))
    after = [np.asarray(t) for t in jax.tree.leaves(net.params)]
    assert any(np.abs(a - b).sum() > 0 for a, b in zip(after, before))


def test_moe_sp_ep_tp_composition_matches_single_device():
    """The full Net-path composition with the dedicated expert axis:
    sequence parallelism (ring attention) x expert parallelism (all-to-all
    dispatch) x tensor parallelism in ONE jitted step, trained 3 steps ==
    the single-device run. Ample capacity so the grouped (per-source-
    shard) capacity semantics coincide with the global one — with drops
    they legitimately differ (GShard grouped dispatch)."""
    def run(dev, sp=1, tp=1, ep=1):
        cfg = transformer_config(seq_len=16, vocab_size=16, feat=16,
                                 nhead=2, nblock=1, num_classes=4,
                                 batch_size=16, dev=dev, moe_experts=4,
                                 seq_parallel=sp, model_parallel=tp)
        cfg = cfg.replace("  nexpert = 4",
                          "  nexpert = 4\n  capacity_factor = 16")
        if ep > 1:
            cfg += "\nexpert_parallel = %d\n" % ep
        net = Net(tokenize(cfg))
        net.init_model()
        rs = np.random.RandomState(0)
        for i in range(3):
            ids = rs.randint(0, 16, (16, 1, 1, 16)).astype(np.float32)
            lab = rs.randint(0, 4, (16, 1)).astype(np.float32)
            net.update(DataBatch(ids, lab))
        return {"%s/%s" % (l, t): np.asarray(w)
                for l, ts in net.params.items() for t, w in ts.items()}

    ref = run("cpu:0")
    par = run("cpu:0-7", sp=2, tp=2, ep=2)
    assert ref.keys() == par.keys()
    for k in ref:
        np.testing.assert_allclose(par[k], ref[k], rtol=2e-3, atol=2e-4,
                                   err_msg=k)


def test_top2_matches_dense_per_token():
    """Top-2 routing with ample capacity: each token's output must equal
    the renormalized-gate sum of its two best experts' FFNs (GShard)."""
    rs = np.random.RandomState(7)
    wg, wu, wd = _weights(rs)
    x = jnp.asarray(rs.randn(24, 8).astype(np.float32))
    out, aux = switch_moe(x, wg, wu, wd, capacity_factor=8.0, top_k=2)

    probs = np.asarray(jax.nn.softmax(x @ wg, axis=-1))
    for t in range(24):
        top2 = np.argsort(probs[t])[::-1][:2]
        g = probs[t, top2] / probs[t, top2].sum()
        ref = 0.0
        for gi, ei in zip(g, top2):
            hdn = np.maximum(np.asarray(x[t]) @ np.asarray(wu[ei]), 0)
            ref = ref + gi * (hdn @ np.asarray(wd[ei]))
        np.testing.assert_allclose(np.asarray(out[t]), ref, rtol=1e-4,
                                   atol=1e-5)
    assert float(aux) > 0


def test_top2_first_choices_win_capacity():
    """Capacity contention: every token 1st-chooses expert 0 and
    2nd-chooses expert 1. Each expert's queue (capacity 2) fills in
    token order — expert 0 with first choices, expert 1 with second
    choices — so tokens 0,1 get BOTH experts and the rest drop to the
    residual entirely."""
    rs = np.random.RandomState(8)
    e, d_model = 2, 8
    wg = jnp.asarray(np.stack([np.full(d_model, 2.0),
                               np.full(d_model, 1.0)], axis=1)
                     .astype(np.float32))
    wu, wd = _weights(rs, e=e)[1:]
    x = jnp.abs(jnp.asarray(rs.randn(8, d_model).astype(np.float32)))
    # capacity = ceil(2*8/2 * 0.25) = 2 per expert
    out, _ = switch_moe(x, wg, wu, wd, capacity_factor=0.25, top_k=2)
    probs = np.asarray(jax.nn.softmax(x @ wg, axis=-1))

    def expert_out(t, ei, gi):
        hdn = np.maximum(np.asarray(x[t]) @ np.asarray(wu[ei]), 0)
        return gi * (hdn @ np.asarray(wd[ei]))

    for t in range(8):
        g = probs[t] / probs[t].sum()
        want = np.zeros(d_model, np.float32)
        # expert 0's queue holds only 1st choices (token order): t<2 kept.
        # expert 1's queue holds only 2nd choices (token order): t<2 kept.
        if t < 2:
            want = want + expert_out(t, 0, g[0]) + expert_out(t, 1, g[1])
        np.testing.assert_allclose(np.asarray(out[t]), want, rtol=1e-4,
                                   atol=1e-5, err_msg=str(t))


def test_top2_gradients_finite():
    rs = np.random.RandomState(9)
    wg, wu, wd = _weights(rs)
    x = jnp.asarray(rs.randn(16, 8).astype(np.float32))

    def loss(xx, g, u, dn):
        out, aux = switch_moe(xx, g, u, dn, capacity_factor=1.0, top_k=2)
        return jnp.sum(out * out) + 0.01 * aux

    grads = jax.grad(loss, argnums=(0, 1, 2, 3))(x, wg, wu, wd)
    for g in grads:
        assert np.isfinite(np.asarray(g)).all()
        assert float(jnp.abs(g).sum()) > 0


def test_dense_rejects_topk():
    rs = np.random.RandomState(10)
    wg, wu, wd = _weights(rs)
    x = jnp.asarray(rs.randn(8, 8).astype(np.float32))
    import pytest
    with pytest.raises(ValueError, match="top_k"):
        switch_moe(x, wg, wu, wd, dispatch="dense", top_k=2)


def test_moe_topk2_transformer_trains():
    cfg = transformer_config(seq_len=16, vocab_size=16, feat=16, nhead=2,
                             nblock=1, num_classes=4, batch_size=16,
                             dev="cpu:0-7", moe_experts=4)
    cfg = cfg.replace("  nexpert = 4", "  nexpert = 4\n  moe_topk = 2")
    net = Net(tokenize(cfg))
    net.init_model()
    rs = np.random.RandomState(0)
    before = [np.asarray(t).copy() for t in jax.tree.leaves(net.params)]
    for i in range(3):
        ids = rs.randint(0, 16, (16, 1, 1, 16)).astype(np.float32)
        lab = rs.randint(0, 4, (16, 1)).astype(np.float32)
        net.update(DataBatch(ids, lab))
    after = [np.asarray(t) for t in jax.tree.leaves(net.params)]
    assert any(np.abs(a - b).sum() > 0 for a, b in zip(after, before))


def test_ragged_matches_sort_when_no_drops():
    """Dropless ragged dispatch == sort dispatch whenever capacity is ample
    (no tokens dropped), for k = 1, 2, 3."""
    rs = np.random.RandomState(11)
    wg, wu, wd = _weights(rs, e=4, d=8, h=16)
    x = jnp.asarray(rs.randn(48, 8).astype(np.float32))
    for k in (1, 2, 3):
        ref, aux_ref = switch_moe(x, wg, wu, wd, capacity_factor=16.0,
                                  dispatch="sort", top_k=k)
        out, aux = switch_moe(x, wg, wu, wd, dispatch="ragged", top_k=k)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-5, err_msg="k=%d" % k)
        np.testing.assert_allclose(float(aux), float(aux_ref), rtol=1e-6)


def test_ragged_is_dropless_under_overflow():
    """Route everything to one expert: sort with tight capacity drops most
    tokens; ragged processes all of them."""
    rs = np.random.RandomState(12)
    _, wu, wd = _weights(rs, e=4, d=8, h=16)
    wg = jnp.zeros((8, 4), jnp.float32).at[:, 2].set(50.0)
    # positive inputs => positive row sums => every token routes to expert 2
    x = jnp.asarray(np.abs(rs.randn(32, 8)).astype(np.float32) + 0.1)
    dropped, _ = switch_moe(x, wg, wu, wd, capacity_factor=1.0,
                            dispatch="sort")
    full, _ = switch_moe(x, wg, wu, wd, dispatch="ragged")
    n_zero_drop = int((np.abs(np.asarray(dropped)).max(-1) < 1e-7).sum())
    n_zero_full = int((np.abs(np.asarray(full)).max(-1) < 1e-7).sum())
    assert n_zero_drop >= 20          # capacity ceil(32/4) = 8 kept
    assert n_zero_full == 0           # every token processed
    # the kept tokens agree between the two paths
    kept = np.abs(np.asarray(dropped)).max(-1) > 1e-7
    np.testing.assert_allclose(np.asarray(full)[kept],
                               np.asarray(dropped)[kept], rtol=1e-4,
                               atol=1e-5)


def test_ragged_gradients_match_sort():
    rs = np.random.RandomState(13)
    wg, wu, wd = _weights(rs, e=4, d=8, h=16)
    x = jnp.asarray(rs.randn(24, 8).astype(np.float32))

    def loss(disp):
        def f(xx, g, u, dn):
            out, aux = switch_moe(xx, g, u, dn, 16.0, dispatch=disp,
                                  top_k=2)
            return jnp.sum(out ** 2) + aux
        return jax.grad(f, argnums=(0, 1, 2, 3))(x, wg, wu, wd)

    gr, gs = loss("ragged"), loss("sort")
    for a, b in zip(gr, gs):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4,
                                   atol=2e-5)


def test_topk3_per_token_reference():
    """top_k=3 against a dense per-token reference: renormalized top-3
    gates, all tokens kept (ample capacity)."""
    rs = np.random.RandomState(14)
    wg, wu, wd = _weights(rs, e=5, d=8, h=16)
    x = jnp.asarray(rs.randn(16, 8).astype(np.float32))
    out, _ = switch_moe(x, wg, wu, wd, capacity_factor=16.0,
                        dispatch="sort", top_k=3)
    probs = np.asarray(jax.nn.softmax(x @ wg, axis=-1))
    for t in range(16):
        top3 = np.argsort(-probs[t])[:3]
        g = probs[t, top3] / probs[t, top3].sum()
        ref = sum(g[j] * (np.maximum(np.asarray(x[t]) @ np.asarray(wu[e]), 0)
                          @ np.asarray(wd[e]))
                  for j, e in enumerate(top3))
        np.testing.assert_allclose(np.asarray(out[t]), ref, rtol=1e-4,
                                   atol=1e-5, err_msg="token %d" % t)


def test_moe_ragged_dispatch_through_config():
    """moe_dispatch=ragged from the config DSL trains and tracks the sort
    path (ample capacity => identical routing)."""
    cfg = transformer_config(seq_len=16, vocab_size=32, feat=16, nhead=2,
                             nblock=1, num_classes=4, batch_size=8,
                             dev="cpu:0", moe_experts=4)
    rs = np.random.RandomState(5)
    x = rs.randint(0, 32, (8, 1, 1, 16)).astype(np.float32)
    y = rs.randint(0, 4, (8, 1)).astype(np.float32)

    nets = {}
    for disp in ("sort", "ragged"):
        net = Net(tokenize(cfg + "\nmoe_dispatch = %s\n"
                                 "capacity_factor = 16\n" % disp))
        net.set_param("seed", "3")
        net.init_model()
        for _ in range(3):
            net.update(DataBatch(x, y))
        nets[disp] = net
    for k in nets["sort"].params:
        for tag in nets["sort"].params[k]:
            np.testing.assert_allclose(
                np.asarray(nets["ragged"].params[k][tag]),
                np.asarray(nets["sort"].params[k][tag]),
                rtol=2e-4, atol=2e-5, err_msg="%s/%s" % (k, tag))


def test_moe_ragged_rejects_expert_parallel():
    """moe_dispatch=ragged is a dropless SEMANTIC choice; the ep>1
    all-to-all path drops overflow tokens, so the combination must fail
    loudly at first trace instead of silently dropping (ADVICE r4)."""
    import pytest
    from cxxnet_tpu.utils.config import ConfigError
    cfg = transformer_config(seq_len=16, vocab_size=16, feat=16, nhead=2,
                             nblock=1, num_classes=4, batch_size=16,
                             dev="cpu:0-7", moe_experts=4)
    cfg += "\nexpert_parallel = 4\nmoe_dispatch = ragged\n"
    net = Net(tokenize(cfg))
    net.init_model()
    rs = np.random.RandomState(0)
    ids = rs.randint(0, 16, (16, 1, 1, 16)).astype(np.float32)
    lab = rs.randint(0, 4, (16, 1)).astype(np.float32)
    with pytest.raises(ConfigError, match="dropless"):
        net.update(DataBatch(ids, lab))
