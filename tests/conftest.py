"""Test harness config: force an 8-virtual-device CPU mesh.

The container's sitecustomize (PYTHONPATH=/root/.axon_site) pre-imports jax
and registers the axon TPU PJRT plugin at interpreter start, but the backend
itself initializes lazily — so switching the platform to CPU in-process works
as long as it happens before anything touches `jax.devices()`. conftest.py is
imported before any test module, which is early enough.
"""

import os

os.environ.setdefault("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] = (
        os.environ["XLA_FLAGS"] + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")

import threading
import time

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.RandomState(0)


@pytest.fixture(autouse=True)
def _no_leaked_background_threads():
    """Leak check (round 6, extended round 7): background threads owned
    by framework objects are namespaced ``cxn-*`` — the async device
    feed's producers (``cxn-device-prefetch-*``, io/device_prefetch.py)
    and the inference server's scheduler (``cxn-serve-scheduler-*``,
    serve/server.py). Any still alive after a test means a
    DevicePrefetcher was not close()d or an InferenceServer was not shut
    down — a real bug (the thread holds the iterator chain / the KV slot
    pool and its device buffers), failed here instead of hanging a later
    test."""
    yield
    # scheduler + printer + any speculative drafter workers (cxn-spec-*:
    # the naming contract for future async drafters — today's drafters
    # run on the scheduler thread, but a leak check that predates the
    # first worker is the cheap kind) + the obs metrics flusher
    # (cxn-obs-flusher-*, obs/export.py — a leaked one keeps appending
    # JSONL snapshots to a closed test's tmp file forever)
    # (the "cxn-serve" prefix also covers the resilience layer's
    # watchdog threads, cxn-serve-watchdog-* — serve/server.py)
    # cxn-fleet-* covers the cross-process router (serve/fleet.py):
    # monitor/pump/respawn threads, RPC reader + dispatch threads, and
    # the worker-stdout drains — all must be gone after shutdown()
    prefixes = ("cxn-device-prefetch", "cxn-serve", "cxn-spec", "cxn-obs",
                "cxn-fleet")
    deadline = time.time() + 5.0
    while True:
        leaked = [t.name for t in threading.enumerate()
                  if t.name.startswith(prefixes)]
        if not leaked or time.time() > deadline:
            break
        time.sleep(0.05)
    assert not leaked, \
        "framework background threads leaked past teardown: %s" % leaked
    # replay-journal leak check (round 15): a server that shut down
    # finalizes every journaled request and clears its journal — a
    # non-empty journal after teardown means admitted requests were
    # abandoned without a terminal state (result() would hang forever)
    from cxxnet_tpu.serve.resilience import live_journals
    leaked_j = [j for j in live_journals() if len(j)]
    assert not leaked_j, \
        "replay journals leaked %s admitted request(s) past teardown" \
        % [len(j) for j in leaked_j]
