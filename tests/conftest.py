"""Test harness config: force an 8-virtual-device CPU mesh.

The container's sitecustomize (PYTHONPATH=/root/.axon_site) pre-imports jax
and registers the axon TPU PJRT plugin at interpreter start, but the backend
itself initializes lazily — so switching the platform to CPU in-process works
as long as it happens before anything touches `jax.devices()`. conftest.py is
imported before any test module, which is early enough.
"""

import os

os.environ.setdefault("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] = (
        os.environ["XLA_FLAGS"] + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.RandomState(0)
