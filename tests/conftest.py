"""Test harness config: force an 8-virtual-device CPU mesh.

The container's sitecustomize (PYTHONPATH=/root/.axon_site) eagerly registers
the axon TPU PJRT plugin at interpreter start; once that has happened, setting
JAX_PLATFORMS=cpu in-process hangs the axon client. So before anything imports
jax we re-exec pytest with PYTHONPATH dropped and the CPU platform forced —
giving every test the 8-device virtual mesh the sharding tests need.
"""

import os
import sys

_SENTINEL = "CXXNET_TPU_TEST_REEXEC"

if os.environ.get(_SENTINEL) != "1" and "jax" not in sys.modules:
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    env[_SENTINEL] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    xla = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in xla:
        env["XLA_FLAGS"] = (xla + " --xla_force_host_platform_device_count=8").strip()
    os.execve(sys.executable,
              [sys.executable, "-m", "pytest"] + sys.argv[1:], env)

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.RandomState(0)
