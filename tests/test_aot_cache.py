"""AOT executable cache (analysis/aot_cache.py + the engine/Net/
gpt_decode fetch points).

The load-bearing invariants:

1. **bit identity** — a cache-hit engine's served tokens equal a
   freshly-compiled engine's AND the solo ``gpt_decode`` oracle, greedy
   and sampled, paged and speculative;
2. **zero compile on warm start** — with a warm cache and the in-process
   program caches cleared (a fresh-process stand-in), building and
   serving performs NO ``/jax/core/compile/*`` work for the cached
   programs (CompileWatch per-label attribution is the witness);
3. **key invalidation** — every key component (config hash, signature,
   extra flags, mesh, devices, backend, jax/jaxlib version) drifting is
   a miss, and the CXN210 validator names the drifting component;
4. **corruption safety** — a truncated/garbage entry logs one warning,
   counts stale, and falls through to a normal compile — never a crash;
5. **recovery** — ``_build_stack()`` after an injected engine fault
   re-resolves every program from the cache (zero new compile seconds);
6. **aot_cache unset is a no-op** — no cache object, no resolved
   programs, the lazy jit path untouched (the rest of the serve suite
   is the real pin).
"""

import glob
import os

import jax
import numpy as np
import pytest

from cxxnet_tpu.analysis import aot_cache as aot_mod
from cxxnet_tpu.models.gpt import GPTConfig, gpt_decode, gpt_init
from cxxnet_tpu.obs import devprof
from cxxnet_tpu.serve import InferenceServer
from cxxnet_tpu.serve import engine as engine_mod

CFG = GPTConfig(vocab_size=32, seq_len=48, n_layer=2, n_head=2, feat=16,
                n_microbatch=1)
PARAMS = gpt_init(jax.random.PRNGKey(5), CFG)

SERVE_LABELS = ("serve_prefill_chunk", "serve_verify_chunk", "serve_tick")


def _prompt(rs, n):
    return rs.randint(0, CFG.vocab_size, (n,)).astype(np.int32)


def _cases(rs):
    """Greedy + sampled + shared-prefix mixed workload."""
    shared = _prompt(rs, 8)
    return [
        dict(p=_prompt(rs, 5), max_tokens=5),
        dict(p=np.concatenate([shared, _prompt(rs, 3)]), max_tokens=5),
        dict(p=np.concatenate([shared, _prompt(rs, 2)]), max_tokens=4,
             temperature=0.8, top_k=5, seed=7),
        dict(p=_prompt(rs, 9), max_tokens=5, temperature=1.1, seed=3),
    ]


def _serve(srv, cases):
    hs = [srv.submit(c["p"], **{k: v for k, v in c.items() if k != "p"})
          for c in cases]
    res = [srv.result(h, timeout=300) for h in hs]
    assert all(r.status == "ok" for r in res), [r.status for r in res]
    return [tuple(int(t) for t in r.tokens) for r in res]


def _serve_compile_seconds():
    """Per-label compile seconds for the serve programs (CompileWatch)."""
    totals = devprof.compile_watch().totals
    return {k: totals.get(k, 0.0) for k in SERVE_LABELS}


# ------------------------------------------------- unit: cache + wrapper
def test_cached_program_roundtrip(tmp_path):
    cache = aot_mod.get_cache(str(tmp_path))
    jit = lambda: jax.jit(lambda x, n: x * 2 + n, static_argnums=(1,))
    x = jax.numpy.ones((4,), np.float32)
    cp = aot_mod.CachedProgram(jit(), "toy", config="c1", extra="e1",
                               static_argnums=(1,), cache=cache)
    np.testing.assert_array_equal(np.asarray(cp(x, 3)), np.full(4, 5.0))
    assert cp.source == "compiled"
    assert cache.stats()["misses"] >= 1
    # a fresh wrapper (fresh-process stand-in) loads instead of compiling
    cp2 = aot_mod.CachedProgram(jit(), "toy", config="c1", extra="e1",
                                static_argnums=(1,), cache=cache)
    h0 = cache.stats()["hits"]
    np.testing.assert_array_equal(np.asarray(cp2(x, 3)), np.full(4, 5.0))
    assert cp2.source == "aot_load" and cache.stats()["hits"] == h0 + 1
    # a drifted static value drops to the plain jit path (and still works)
    np.testing.assert_array_equal(np.asarray(cp2(x, 5)), np.full(4, 7.0))
    # attribute transparency: .lower reaches the wrapped jit
    assert hasattr(cp2, "lower")


def test_key_invalidation_names_each_component(tmp_path):
    """Every key component drifting is (a) a different digest — a miss —
    and (b) named by stale_entries (the CXN210 source)."""
    cache = aot_mod.get_cache(str(tmp_path))
    x = jax.numpy.ones((3,), np.float32)
    comp = cache.components("p", (x,), extra="A", config="c1")
    compiled = jax.jit(lambda x: x + 1).lower(x).compile()
    assert cache.store(comp, compiled)
    assert cache.load(dict(comp)) is not None
    for field, val in [("config", "c2"), ("extra", "B|interpret=0"),
                       ("mesh", "model=2"), ("devices", "7:TPU v99"),
                       ("backend", "tpu"), ("jax", "9.9.9"),
                       ("jaxlib", "9.9.8"),
                       ("signature", comp["signature"] + "x")]:
        drifted = dict(comp, **{field: val})
        assert cache.digest(drifted) != cache.digest(comp)
        assert cache.load(drifted) is None          # miss, not a crash
        stale = cache.stale_entries(drifted)
        assert stale and any(field in d for _, d in stale), \
            (field, stale)
    # an orphaned payload (crash between the .bin and .json writes of
    # the pair) must still surface in the scan, as "unreadable meta"
    orphan = tmp_path / "p" / ("0" * 64 + ".bin")
    orphan.write_bytes(b"payload without a sidecar")
    stale = cache.stale_entries(dict(comp, config="c3"))
    assert any(d.get("entry", ("",))[0] == "unreadable meta"
               for _, d in stale), stale
    orphan.unlink()


def test_faked_jax_version_invalidates(tmp_path, monkeypatch):
    cache = aot_mod.get_cache(str(tmp_path))
    x = jax.numpy.ones((3,), np.float32)
    comp = cache.components("p", (x,), config="c1")
    cache.store(comp, jax.jit(lambda x: x + 1).lower(x).compile())
    monkeypatch.setattr(aot_mod, "_versions", lambda: ("99.0.0", "99.0.0"))
    comp2 = cache.components("p", (x,), config="c1")
    assert cache.load(comp2) is None
    stale = cache.stale_entries(comp2)
    assert stale and all("jax" in drift for _, drift in stale)
    assert stale[0][1]["jax"] == (jax.__version__, "99.0.0")


def test_corrupted_entry_falls_through(tmp_path, capfd):
    cache = aot_mod.get_cache(str(tmp_path))
    x = jax.numpy.ones((3,), np.float32)
    comp = cache.components("p", (x,), config="c1")
    cache.store(comp, jax.jit(lambda x: x + 1).lower(x).compile())
    for b in glob.glob(str(tmp_path / "p" / "*.bin")):
        with open(b, "wb") as f:
            f.write(b"garbage")
    s0 = cache.stats()["stale"]
    assert cache.load(comp) is None
    assert cache.stats()["stale"] == s0 + 1
    assert "recompiling" in capfd.readouterr().err


# --------------------------------------------- serve engine: warm start
def _populate(tmp_path, **kw):
    """One throwaway server build that compiles + persists everything."""
    with InferenceServer(CFG, PARAMS, slots=2, queue=16, prefill_chunk=4,
                         aot_cache=str(tmp_path), **kw) as srv:
        assert set(srv._engine.aot_status()) >= {"serve_prefill_chunk",
                                                 "serve_tick"}
        return srv._engine.aot_status()


def test_warm_start_bit_identical_and_zero_compile(tmp_path):
    """The acceptance pin: warm-cache startup loads every serve program
    (zero /jax/core/compile/* seconds for the cached labels) and serves
    bit-identical tokens — greedy AND sampled, paged + prefix sharing."""
    rs = np.random.RandomState(0)
    cases = _cases(rs)
    with InferenceServer(CFG, PARAMS, slots=2, queue=16,
                         prefill_chunk=4) as srv:
        ref = _serve(srv, cases)
    status = _populate(tmp_path)
    assert all(v == "compiled" for v in status.values())
    # fresh-process stand-in: drop every in-process compiled program
    engine_mod.clear_program_caches()
    before = _serve_compile_seconds()
    from cxxnet_tpu.obs.trace import TID_ENGINE, Tracer
    tr = Tracer()
    with InferenceServer(CFG, PARAMS, slots=2, queue=16, prefill_chunk=4,
                         aot_cache=str(tmp_path), tracer=tr) as srv:
        status = srv._engine.aot_status()
        got = _serve(srv, cases)
        m = srv.metrics()
    assert all(v == "aot_load" for v in status.values()), status
    assert got == ref
    assert _serve_compile_seconds() == before, \
        "warm start must not compile any cached serve program"
    assert m["aot_cache"]["hits"] >= 2
    # the compile spans of a cold start are REPLACED by aot_load spans
    # on the engine trace track (one per loaded program); the small
    # uncached copy programs (COW faults) may still compile — only the
    # CACHED labels must show zero compile spans
    spans = tr.spans(TID_ENGINE)
    assert sum(1 for s in spans if s.name == "aot_load") >= 2
    compiled_fns = {(s.args or {}).get("fn") for s in spans
                    if s.name == "compile"}
    assert not (compiled_fns & set(SERVE_LABELS)), compiled_fns


def test_warm_start_speculative_identity(tmp_path):
    rs = np.random.RandomState(3)
    base = _prompt(rs, 6)
    prompt = np.concatenate([base, base, base])     # n-gram bait
    kw = dict(slots=2, queue=8, prefill_chunk=4, spec_mode="ngram",
              spec_len=3)
    with InferenceServer(CFG, PARAMS, **kw) as srv:
        ref = srv.result(srv.submit(prompt, max_tokens=8), timeout=300)
    _populate(tmp_path, spec_mode="ngram", spec_len=3)
    engine_mod.clear_program_caches()
    before = _serve_compile_seconds()
    with InferenceServer(CFG, PARAMS, aot_cache=str(tmp_path),
                         **kw) as srv:
        assert srv._engine.aot_status().get("serve_verify_chunk") \
            == "aot_load"
        res = srv.result(srv.submit(prompt, max_tokens=8), timeout=300)
        m = srv.metrics()
    assert res.status == "ok" and m["spec_forwards"] >= 1
    np.testing.assert_array_equal(res.tokens, ref.tokens)
    assert _serve_compile_seconds() == before


def test_corrupt_cache_serves_by_compiling(tmp_path, capfd):
    rs = np.random.RandomState(1)
    cases = _cases(rs)[:2]
    _populate(tmp_path)
    for b in glob.glob(str(tmp_path / "*" / "*.bin")):
        with open(b, "wb") as f:
            f.write(b"\x00garbage")
    engine_mod.clear_program_caches()
    cache = aot_mod.get_cache(str(tmp_path))
    s0 = cache.stats()["stale"]
    with InferenceServer(CFG, PARAMS, slots=2, queue=16, prefill_chunk=4,
                         aot_cache=str(tmp_path)) as srv:
        assert all(v == "compiled"
                   for v in srv._engine.aot_status().values())
        got = _serve(srv, cases)
    assert cache.stats()["stale"] > s0
    assert "recompiling" in capfd.readouterr().err
    with InferenceServer(CFG, PARAMS, slots=2, queue=16,
                         prefill_chunk=4) as srv:
        assert got == _serve(srv, cases)


def test_recovery_rebuilds_from_cache(tmp_path):
    """PR 9's _build_stack() restart path: with a warm cache (and the
    in-process program caches cleared — a supervisor-restart stand-in),
    an injected engine fault recovers by LOADING every program; the
    replayed stream is bit-identical and no cached label compiles."""
    rs = np.random.RandomState(4)
    cases = [dict(p=_prompt(rs, 7), max_tokens=8),
             dict(p=_prompt(rs, 5), max_tokens=6)]
    with InferenceServer(CFG, PARAMS, slots=2, queue=16,
                         prefill_chunk=4) as srv:
        ref = _serve(srv, cases)
    _populate(tmp_path)
    engine_mod.clear_program_caches()
    before = _serve_compile_seconds()
    with InferenceServer(CFG, PARAMS, slots=2, queue=16, prefill_chunk=4,
                         aot_cache=str(tmp_path), chaos="tick_raise@2",
                         max_restarts=2) as srv:
        got = _serve(srv, cases)
        m = srv.metrics()
    assert m["resilience"]["restarts"] >= 1, \
        "the injected fault must trigger recovery"
    assert got == ref
    assert _serve_compile_seconds() == before, \
        "recovery must re-resolve programs from the cache, not compile"


def test_unwritable_cache_dir_degrades_gracefully(tmp_path, capfd):
    """aot_cache pointing at an unusable path: ONE warn, metrics show
    misses and zero hits, the engine builds by compiling and serves."""
    rs = np.random.RandomState(2)
    notadir = tmp_path / "occupied"
    notadir.write_text("not a directory")
    cache = aot_mod.get_cache(str(notadir))
    m0 = cache.stats()
    with InferenceServer(CFG, PARAMS, slots=2, queue=8, prefill_chunk=4,
                         aot_cache=str(notadir)) as srv:
        assert all(v == "compiled"
                   for v in srv._engine.aot_status().values())
        res = srv.result(srv.submit(_prompt(rs, 6), max_tokens=5),
                         timeout=300)
    assert res.status == "ok"
    m1 = cache.stats()
    assert m1["misses"] > m0["misses"] and m1["hits"] == m0["hits"]
    err = capfd.readouterr().err
    # exactly ONE warn, not one per program (the tmp path itself
    # contains "unwritable" — count the message tail instead)
    assert err.count("compiled programs will not persist") == 1, err
    # the failed store MEMOIZED the executables: an in-process rebuild
    # (what a watchdog recovery does) re-resolves without paying XLA
    # again — armed-but-unwritable must never be slower than cache-off
    t0 = _serve_compile_seconds()
    with InferenceServer(CFG, PARAMS, slots=2, queue=8, prefill_chunk=4,
                         aot_cache=str(notadir)) as srv2:
        assert all(v == "aot_load"
                   for v in srv2._engine.aot_status().values())
    assert _serve_compile_seconds() == t0


def test_unset_is_a_noop():
    with InferenceServer(CFG, PARAMS, slots=2, queue=8,
                         prefill_chunk=4) as srv:
        assert srv._aot is None
        assert srv._engine.aot_status() == {}
        assert "aot_cache" not in srv.metrics()


# ------------------------------------------------------- CXN210 validator
def test_artifact_validator_flags_stale(tmp_path, monkeypatch):
    from cxxnet_tpu.analysis.step_audit import audit_aot_artifacts
    _populate(tmp_path)
    # an abstract validator engine sized EXACTLY like the server's
    # (same auto_num_blocks inputs) — its keys must match the artifacts
    veng = engine_mod.DecodeEngine(
        CFG, PARAMS, slots=2, prefill_chunk=4, abstract=True,
        num_blocks=engine_mod.auto_num_blocks(CFG, 2, 4, prefix_mb=32.0),
        spec_len=0)
    report, infos = audit_aot_artifacts(veng, str(tmp_path))
    # the matching chunk/tick artifacts audit clean (donation is off on
    # the CPU mesh, so no aliasing is expected — no CXN201 either way)
    assert not any(f.rule == "CXN210" for f in report.findings), \
        report.format()
    assert {i["label"] for i in infos} >= {"serve_prefill_chunk",
                                           "serve_tick"}
    # a sibling artifact for ANOTHER replica's device block (same key,
    # devices component only) is NOT stale — the router placement story
    cache = aot_mod.get_cache(str(tmp_path))
    row = [s for s in veng.lint_specs(donate=None)
           if s[0] == "serve_tick"][0]
    comp = cache.components("serve_tick", row[2], donate_argnums=row[3],
                            extra=veng.aot_extra("serve_tick"),
                            config=aot_mod.config_hash(veng._cfg_key))
    x = jax.numpy.ones((2,), np.float32)
    cache.store(dict(comp, devices="7:cpu"),
                jax.jit(lambda x: x + 1).lower(x).compile())
    report, _ = audit_aot_artifacts(veng, str(tmp_path))
    assert not any(f.rule == "CXN210" for f in report.findings), \
        report.format()
    # fake a jax upgrade: every entry is now stale, CXN210 names "jax"
    monkeypatch.setattr(aot_mod, "_versions", lambda: ("99.0.0", "99.0.0"))
    report, _ = audit_aot_artifacts(veng, str(tmp_path))
    stale = [f for f in report.findings if f.rule == "CXN210"]
    assert stale and all("jax" in f.message for f in stale), \
        report.format()
    assert report.exit_code() != 0          # fails CI in validator mode


# ------------------------------------------------------ Net + gpt_decode
NET_CONF = """
netconfig=start
layer[+1] = fullc:fc1
  nhidden = 8
  init_sigma = 0.1
layer[+1] = relu
layer[+1] = fullc:fc2
  nhidden = 4
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 1,1,6
batch_size = 8
eta = 0.1
seed = 5
"""


def _net_run(tmp_path=None, steps=3):
    from cxxnet_tpu import Net
    from cxxnet_tpu.utils.config import tokenize
    net = Net(tokenize(NET_CONF))
    if tmp_path is not None:
        net.set_param("aot_cache", str(tmp_path))
    net.init_model()
    rs = np.random.RandomState(7)
    for _ in range(steps):
        class B:
            data = rs.rand(8, 1, 1, 6).astype(np.float32)
            label = rs.randint(0, 4, (8, 1)).astype(np.float32)
            extra_data = []
            num_batch_padd = 0
        net.update(B)
    return net


def test_net_train_warm_start(tmp_path):
    ref = _net_run()
    a = _net_run(tmp_path)
    assert a._jit_update.source == "compiled"
    before = dict(devprof.compile_watch().totals).get("net_update", 0.0)
    b = _net_run(tmp_path)                  # fresh Net = fresh jit objects
    assert b._jit_update.source == "aot_load"
    after = dict(devprof.compile_watch().totals).get("net_update", 0.0)
    assert after == before, "warm trainer startup must not recompile " \
        "net_update"
    for lk, tags in ref.params.items():
        for tag, w in tags.items():
            np.testing.assert_array_equal(np.asarray(b.params[lk][tag]),
                                          np.asarray(w),
                                          err_msg="%s/%s" % (lk, tag))


def test_gpt_decode_warm(tmp_path):
    from cxxnet_tpu.models import gpt as gpt_m
    rs = np.random.RandomState(9)
    prompt = _prompt(rs, 6)[None]
    ref = np.asarray(gpt_decode(PARAMS, prompt, 5, CFG))
    aot_mod.configure(str(tmp_path))
    try:
        gpt_m._decode_fn.cache_clear()
        out1 = np.asarray(gpt_decode(PARAMS, prompt, 5, CFG))
        gpt_m._decode_fn.cache_clear()      # fresh-process stand-in
        out2 = np.asarray(gpt_decode(PARAMS, prompt, 5, CFG))
        cache = aot_mod.get_cache(str(tmp_path))
        assert cache.stats()["hits"] >= 1
    finally:
        aot_mod.reset_configured()
        gpt_m._decode_fn.cache_clear()
    np.testing.assert_array_equal(out1, ref)
    np.testing.assert_array_equal(out2, ref)
