"""End-to-end: synthetic MNIST-format data -> config -> CLI train -> eval
improves -> checkpoint/resume -> predict/extract. The examples-as-integration-
tests strategy of the reference (SURVEY §4.5), runnable hermetically."""

import gzip
import os
import struct
import subprocess
import sys

import numpy as np
import pytest

from cxxnet_tpu import Net
from cxxnet_tpu.cli import LearnTask
from cxxnet_tpu.io import create_iterator
from cxxnet_tpu.utils.config import tokenize


def write_idx_images(path, images):
    """images: (n, rows, cols) uint8."""
    n, r, c = images.shape
    with gzip.open(path, "wb") as f:
        f.write(struct.pack(">iiii", 2051, n, r, c))
        f.write(images.tobytes())


def write_idx_labels(path, labels):
    with gzip.open(path, "wb") as f:
        f.write(struct.pack(">ii", 2049, labels.shape[0]))
        f.write(labels.astype(np.uint8).tobytes())


@pytest.fixture(scope="module")
def synth_mnist(tmp_path_factory):
    """Linearly-separable 10-class 8x8 'digits'."""
    d = tmp_path_factory.mktemp("mnist")
    rs = np.random.RandomState(42)
    protos = rs.rand(10, 8, 8) * 255
    n_train, n_test = 512, 128

    def gen(n):
        y = rs.randint(0, 10, n)
        x = protos[y] + rs.randn(n, 8, 8) * 20
        return np.clip(x, 0, 255).astype(np.uint8), y

    xtr, ytr = gen(n_train)
    xte, yte = gen(n_test)
    write_idx_images(str(d / "train-img.gz"), xtr)
    write_idx_labels(str(d / "train-lab.gz"), ytr)
    write_idx_images(str(d / "test-img.gz"), xte)
    write_idx_labels(str(d / "test-lab.gz"), yte)
    return d


CONF = """
data = train
iter = mnist
    path_img = "{d}/train-img.gz"
    path_label = "{d}/train-lab.gz"
    shuffle = 1
iter = end
eval = test
iter = mnist
    path_img = "{d}/test-img.gz"
    path_label = "{d}/test-lab.gz"
iter = end

netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 64
  init_sigma = 0.05
layer[+1:sg1] = sigmoid:se1
layer[sg1->fc2] = fullc:fc2
  nhidden = 10
  init_sigma = 0.05
layer[+0] = softmax
netconfig=end

input_shape = 1,1,64
batch_size = 64
dev = cpu
save_model = 2
max_round = 4
num_round = 4
train_eval = 1
random_type = gaussian
eta = 0.2
momentum = 0.9
wd  = 0.0
metric = error
eval_train = 1
model_dir = {md}
"""


def test_cli_train_and_resume(synth_mnist, tmp_path, capfd):
    md = tmp_path / "models"
    conf = tmp_path / "mnist.conf"
    conf.write_text(CONF.format(d=synth_mnist, md=md))

    task = LearnTask()
    assert task.run([str(conf)]) == 0
    err = capfd.readouterr().err
    lines = [l for l in err.splitlines() if l.startswith("[")]
    assert len(lines) == 4
    # eval error should drop well below chance (0.9) by round 4
    last_err = float(lines[-1].split("test-error:")[1].split()[0])
    assert last_err < 0.3, "training did not converge: %s" % lines
    # snapshots written every save_model=2 rounds
    assert sorted(os.listdir(md)) == ["0002.model", "0004.model"]

    # resume with continue=1 runs rounds 5..6
    task2 = LearnTask()
    assert task2.run([str(conf), "continue=1", "num_round=6"]) == 0
    err2 = capfd.readouterr().err
    lines2 = [l for l in err2.splitlines() if l.startswith("[")]
    assert lines2 and lines2[0].startswith("[5]")


def test_predict_and_extract(synth_mnist, tmp_path, capfd):
    md = tmp_path / "models"
    conf = tmp_path / "mnist.conf"
    conf.write_text(CONF.format(d=synth_mnist, md=md))
    LearnTask().run([str(conf), "num_round=3", "max_round=3", "save_model=3"])
    capfd.readouterr()

    pred_file = tmp_path / "pred.txt"
    pred_cfg = tmp_path / "pred.conf"
    pred_cfg.write_text(
        CONF.format(d=synth_mnist, md=md) +
        "\npred = %s\niter = mnist\npath_img = \"%s/test-img.gz\"\n"
        "path_label = \"%s/test-lab.gz\"\niter = end\n"
        % (pred_file, synth_mnist, synth_mnist))
    LearnTask().run([str(pred_cfg), "task=pred",
                     "model_in=%s" % (md / "0003.model")])
    preds = np.loadtxt(pred_file)
    assert preds.shape[0] == 128
    assert set(np.unique(preds)).issubset(set(range(10)))

    # extract features from the hidden node by name
    ex_file = tmp_path / "feat.txt"
    ex_cfg = tmp_path / "ex.conf"
    ex_cfg.write_text(
        CONF.format(d=synth_mnist, md=md) +
        "\npred = %s\niter = mnist\npath_img = \"%s/test-img.gz\"\n"
        "path_label = \"%s/test-lab.gz\"\niter = end\n"
        % (ex_file, synth_mnist, synth_mnist))
    LearnTask().run([str(ex_cfg), "task=extract", "extract_node_name=sg1",
                     "model_in=%s" % (md / "0003.model")])
    feats = np.loadtxt(ex_file)
    assert feats.shape == (128, 64)


def test_checkpoint_roundtrip(synth_mnist, tmp_path):
    cfg = tokenize(CONF.format(d=synth_mnist, md=tmp_path))
    net = Net([p for p in cfg if p[0] not in ("data", "eval", "iter",
                                              "path_img", "path_label",
                                              "shuffle")])
    net.init_model()
    w0 = net.get_weight("fc1", "wmat")
    path = str(tmp_path / "m.model")
    net.save_model(path)

    net2 = Net([p for p in cfg if p[0] not in ("data", "eval", "iter",
                                               "path_img", "path_label",
                                               "shuffle")])
    net2.load_model(path)
    np.testing.assert_allclose(net2.get_weight("fc1", "wmat"), w0)


def test_finetune_copy(synth_mnist, tmp_path):
    base_cfg = [p for p in tokenize(CONF.format(d=synth_mnist, md=tmp_path))
                if p[0] not in ("data", "eval", "iter", "path_img",
                                "path_label", "shuffle")]
    a = Net(base_cfg)
    a.init_model()
    b = Net(base_cfg)
    b.init_model()
    b.copy_model_from(a)
    np.testing.assert_allclose(b.get_weight("fc1", "wmat"),
                               a.get_weight("fc1", "wmat"))
    assert b.epoch_counter == 0


def test_set_get_weight(synth_mnist, tmp_path):
    base_cfg = [p for p in tokenize(CONF.format(d=synth_mnist, md=tmp_path))
                if p[0] not in ("data", "eval", "iter", "path_img",
                                "path_label", "shuffle")]
    net = Net(base_cfg)
    net.init_model()
    w = net.get_weight("fc2", "wmat")
    new = np.zeros_like(w)
    net.set_weight("fc2", "wmat", new)
    np.testing.assert_allclose(net.get_weight("fc2", "wmat"), new)


def test_bf16_feed_into_f32_net_stays_f32():
    """A `data_dtype = bfloat16` pipeline feeding a float32 net must not
    downgrade the compute dtype (layers derive it from the data node)."""
    import ml_dtypes
    import jax.numpy as jnp

    net = Net(tokenize("""
netconfig=start
layer[+1] = fullc:fc1
  nhidden = 4
layer[+0] = softmax
netconfig=end
input_shape = 1,1,8
batch_size = 8
dev = cpu
"""))
    net.init_model()
    bf16 = np.zeros((8, 1, 1, 8), ml_dtypes.bfloat16)
    f32 = net._host_array(bf16)
    assert f32.dtype == ml_dtypes.bfloat16     # passthrough at the feed...
    nodes = net._entry_nodes(jnp.asarray(bf16), [])
    assert nodes[0].dtype == jnp.float32       # ...forced back in the step


def test_cli_bf16_injects_pipeline_dtype(synth_mnist, tmp_path, capfd):
    """precision=bfloat16 configs get `data_dtype = bfloat16` injected into
    their iterator sections (conversion in the pipeline, CLI _create_
    iterators) and still converge."""
    import ml_dtypes

    conf = tmp_path / "mnist.conf"
    conf.write_text(CONF.format(d=synth_mnist, md=tmp_path / "m"))
    task = LearnTask()
    assert task.run([str(conf), "precision=bfloat16", "num_round=3",
                     "max_round=3"]) == 0
    err = capfd.readouterr().err
    lines = [l for l in err.splitlines() if l.startswith("[")]
    last_err = float(lines[-1].split("test-error:")[1].split()[0])
    assert last_err < 0.3, lines
    # the train iterator's batches really are compute-dtype
    task.itr_train.before_first()
    assert task.itr_train.next()
    assert task.itr_train.value().data.dtype == ml_dtypes.bfloat16
    # eval section got the injection too
    task.itr_evals[0].before_first()
    assert task.itr_evals[0].next()
    assert task.itr_evals[0].value().data.dtype == ml_dtypes.bfloat16


def test_forward_iter_matches_per_batch_predict(synth_mnist, tmp_path):
    """The double-buffered forward_iter must yield exactly what the
    per-batch predict/extract calls produced (pipelining must not change
    values, order, or padded-tail exclusion)."""
    conf = tmp_path / "m.conf"
    conf.write_text(CONF.format(d=synth_mnist, md=tmp_path / "models"))
    task = LearnTask()
    task.run([str(conf), "num_round=1", "max_round=1"])
    net = task.net

    def make_it():
        # a FRESH chain per pass, and no threadbuffer: the prefetcher
        # advances the base eagerly, which would hand the second pass
        # different batches. This test pins forward_iter's VALUE
        # equivalence, not the prefetch machinery (test_io covers that).
        # NB the mnist iterator serves FULL batches only and drops the
        # 128 % 48 = 32 tail — exactly the reference's MNISTIterator
        # (iter_mnist-inl.hpp:62-71; round_batch wrapping lives in the
        # instance-level batch processor, not the in-memory iterators)
        it = create_iterator([
            ("iter", "mnist"),
            ("path_img", "%s/test-img.gz" % synth_mnist),
            ("path_label", "%s/test-lab.gz" % synth_mnist),
            ("batch_size", "48"),
            ("label_width", "1"), ("input_shape", "1,1,64"),
        ])
        it.init()
        return it

    it1 = make_it()
    serial = []
    it1.before_first()
    while it1.next():
        serial.append(net.predict(it1.value()))
    if hasattr(it1, "close"):
        it1.close()

    it2 = make_it()
    piped = []
    for out in net.forward_iter(it2):
        out = out.reshape(out.shape[0], -1)
        piped.append(out[:, 0] if out.shape[1] == 1
                     else np.argmax(out, axis=1).astype(np.float32))
    if hasattr(it2, "close"):
        it2.close()
    assert len(serial) == len(piped) and len(serial) == 2
    for a, b in zip(serial, piped):
        np.testing.assert_array_equal(a, b)
