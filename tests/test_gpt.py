"""4D-parallel GPT flagship: dp x pp x sp x tp in one jitted train step."""

import jax
import numpy as np
import pytest

from cxxnet_tpu.models.gpt import (GPTConfig, gpt_init, gpt_loss,
                                   gpt_place, make_train_step)
from cxxnet_tpu.parallel.mesh import make_mesh

CFG = GPTConfig(vocab_size=32, seq_len=16, n_layer=4, n_head=4, feat=32,
                n_microbatch=2)


def _ids(seed, n=8):
    rs = np.random.RandomState(seed)
    # deterministic structure: next token = (token + 1) % 8
    start = rs.randint(0, 8, (n, 1))
    seq = (start + np.arange(CFG.seq_len)) % 8
    return jax.numpy.asarray(seq.astype(np.int32))


def _run(mesh, steps):
    params = gpt_place(gpt_init(jax.random.PRNGKey(0), CFG), mesh)
    mom = jax.tree.map(jax.numpy.zeros_like, params)
    mom = gpt_place(mom, mesh)
    step = make_train_step(CFG, mesh)
    losses = []
    for i in range(steps):
        params, mom, loss = step(params, mom, _ids(i))
        losses.append(float(loss))
    return params, losses


def test_gpt_learns_single_device():
    _, losses = _run(make_mesh("cpu:0"), 25)
    assert losses[-1] < losses[0] * 0.5, losses


@pytest.mark.parametrize("axes", [
    dict(pipeline_parallel=2, seq_parallel=2, model_parallel=2),  # pp,sp,tp
    dict(pipeline_parallel=4),                                    # dp2 x pp4
    dict(seq_parallel=4, model_parallel=2),                       # sp4 x tp2
])
def test_gpt_4d_parallel_matches_single_device(axes):
    ref_params, ref_losses = _run(make_mesh("cpu:0"), 4)
    par_params, par_losses = _run(make_mesh("cpu:0-7", **axes), 4)
    np.testing.assert_allclose(par_losses, ref_losses, rtol=2e-4, atol=2e-4)
    for a, b in zip(jax.tree.leaves(jax.tree.map(np.asarray, par_params)),
                    jax.tree.leaves(jax.tree.map(np.asarray, ref_params))):
        np.testing.assert_allclose(a, b, rtol=2e-3, atol=2e-4)
