"""4D-parallel GPT flagship: dp x pp x sp x tp in one jitted train step."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cxxnet_tpu.models.gpt import (GPTConfig, gpt_init, gpt_loss,
                                   gpt_place, make_train_step)
from cxxnet_tpu.parallel.mesh import make_mesh

CFG = GPTConfig(vocab_size=32, seq_len=16, n_layer=4, n_head=4, feat=32,
                n_microbatch=2)


def _ids(seed, n=8):
    rs = np.random.RandomState(seed)
    # deterministic structure: next token = (token + 1) % 8
    start = rs.randint(0, 8, (n, 1))
    seq = (start + np.arange(CFG.seq_len)) % 8
    return jax.numpy.asarray(seq.astype(np.int32))


def _run(mesh, steps):
    params = gpt_place(gpt_init(jax.random.PRNGKey(0), CFG), mesh)
    mom = jax.tree.map(jax.numpy.zeros_like, params)
    mom = gpt_place(mom, mesh)
    step = make_train_step(CFG, mesh)
    losses = []
    for i in range(steps):
        params, mom, loss = step(params, mom, _ids(i))
        losses.append(float(loss))
    return params, losses


def test_gpt_learns_single_device():
    _, losses = _run(make_mesh("cpu:0"), 25)
    assert losses[-1] < losses[0] * 0.5, losses


@pytest.mark.parametrize("axes", [
    dict(pipeline_parallel=2, seq_parallel=2, model_parallel=2),  # pp,sp,tp
    dict(pipeline_parallel=4),                                    # dp2 x pp4
    dict(seq_parallel=4, model_parallel=2),                       # sp4 x tp2
])
def test_gpt_4d_parallel_matches_single_device(axes):
    ref_params, ref_losses = _run(make_mesh("cpu:0"), 4)
    par_params, par_losses = _run(make_mesh("cpu:0-7", **axes), 4)
    np.testing.assert_allclose(par_losses, ref_losses, rtol=2e-4, atol=2e-4)
    for a, b in zip(jax.tree.leaves(jax.tree.map(np.asarray, par_params)),
                    jax.tree.leaves(jax.tree.map(np.asarray, ref_params))):
        np.testing.assert_allclose(a, b, rtol=2e-3, atol=2e-4)


def test_decode_matches_naive_greedy():
    """KV-cache decode == position-by-position full-forward greedy."""
    from cxxnet_tpu.models.gpt import gpt_decode, gpt_logits
    cfg = GPTConfig(vocab_size=32, seq_len=24, n_layer=2, n_head=4, feat=32,
                    n_microbatch=1)
    mesh = make_mesh("cpu:0-7")
    params = gpt_place(gpt_init(jax.random.PRNGKey(5), cfg), mesh)
    rs = np.random.RandomState(3)
    prompt = jax.numpy.asarray(rs.randint(0, 32, (8, 8)).astype(np.int32))

    out = np.asarray(gpt_decode(params, prompt, 10, cfg, mesh))
    assert out.shape == (8, 18)

    # naive: full forward each step, argmax at the last filled position
    ids = np.zeros((8, cfg.seq_len), np.int32)
    ids[:, :8] = np.asarray(prompt)
    for pos in range(8, 18):
        logits = gpt_logits(params, jax.numpy.asarray(ids[:, :pos]), cfg,
                            mesh)
        ids[:, pos] = np.argmax(np.asarray(logits)[:, pos - 1], axis=-1)
    np.testing.assert_array_equal(out, ids[:, :18])


def test_decode_tp_matches_single_device():
    from cxxnet_tpu.models.gpt import gpt_decode
    cfg = GPTConfig(vocab_size=32, seq_len=24, n_layer=2, n_head=4, feat=32,
                    n_microbatch=1)
    params0 = gpt_init(jax.random.PRNGKey(6), cfg)
    rs = np.random.RandomState(4)
    prompt = jax.numpy.asarray(rs.randint(0, 32, (4, 6)).astype(np.int32))

    mesh1 = make_mesh("cpu:0")
    ref = np.asarray(gpt_decode(gpt_place(params0, mesh1), prompt, 8, cfg,
                                mesh1))
    mesh2 = make_mesh("cpu:0-7", model_parallel=2)
    out = np.asarray(gpt_decode(gpt_place(params0, mesh2), prompt, 8, cfg,
                                mesh2))
    np.testing.assert_array_equal(ref, out)


def test_decode_sampling_reproducible():
    from cxxnet_tpu.models.gpt import gpt_decode
    cfg = GPTConfig(vocab_size=32, seq_len=24, n_layer=2, n_head=4, feat=32,
                    n_microbatch=1)
    mesh = make_mesh("cpu:0")
    params = gpt_place(gpt_init(jax.random.PRNGKey(7), cfg), mesh)
    prompt = jax.numpy.asarray(np.zeros((2, 4), np.int32))
    key = jax.random.PRNGKey(42)
    a = np.asarray(gpt_decode(params, prompt, 6, cfg, mesh, temperature=1.0,
                              rng=key))
    b = np.asarray(gpt_decode(params, prompt, 6, cfg, mesh, temperature=1.0,
                              rng=key))
    np.testing.assert_array_equal(a, b)
    import pytest as _pytest
    with _pytest.raises(ValueError, match="rng"):
        gpt_decode(params, prompt, 6, cfg, mesh, temperature=1.0)


def test_decode_validates_max_new():
    from cxxnet_tpu.models.gpt import gpt_decode
    cfg = GPTConfig(vocab_size=32, seq_len=24, n_layer=2, n_head=4, feat=32,
                    n_microbatch=1)
    mesh = make_mesh("cpu:0")
    params = gpt_place(gpt_init(jax.random.PRNGKey(8), cfg), mesh)
    prompt = jax.numpy.asarray(np.zeros((2, 4), np.int32))
    with pytest.raises(ValueError, match="max_new"):
        gpt_decode(params, prompt, 0, cfg, mesh)
    with pytest.raises(ValueError, match="max_new"):
        gpt_decode(params, prompt, -2, cfg, mesh)
    with pytest.raises(ValueError, match="exceeds"):
        gpt_decode(params, prompt, 100, cfg, mesh)


def test_decode_jit_cache_reused():
    import time
    from cxxnet_tpu.models.gpt import gpt_decode
    cfg = GPTConfig(vocab_size=32, seq_len=24, n_layer=2, n_head=4, feat=32,
                    n_microbatch=1)
    mesh = make_mesh("cpu:0")
    params = gpt_place(gpt_init(jax.random.PRNGKey(9), cfg), mesh)
    prompt = jax.numpy.asarray(np.zeros((2, 4), np.int32))
    out1 = gpt_decode(params, prompt, 8, cfg, mesh)
    t0 = time.perf_counter()
    out2 = gpt_decode(params, prompt, 8, cfg, mesh)
    dt = time.perf_counter() - t0
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
    assert dt < 0.5, "second decode call should hit the jit cache (%.2fs)" % dt


def test_remat_matches_no_remat():
    """jax.checkpoint per block recomputes activations in backward — the
    losses must be identical (same math, f32)."""
    import dataclasses
    cfg_r = dataclasses.replace(CFG, remat=True)
    mesh = make_mesh("cpu:0-7", pipeline_parallel=2, model_parallel=2)
    params = gpt_place(gpt_init(jax.random.PRNGKey(0), CFG), mesh)
    mom = gpt_place(jax.tree.map(jax.numpy.zeros_like, params), mesh)
    # the step donates its inputs — the two runs need separate trees
    p2 = gpt_place(gpt_init(jax.random.PRNGKey(0), CFG), mesh)
    m2 = gpt_place(jax.tree.map(jax.numpy.zeros_like, p2), mesh)
    step_a = make_train_step(CFG, mesh)
    step_r = make_train_step(cfg_r, mesh)
    for i in range(3):
        params, mom, la = step_a(params, mom, _ids(i))
        p2, m2, lr = step_r(p2, m2, _ids(i))
        np.testing.assert_allclose(float(la), float(lr), rtol=1e-6)


def test_adam_learns_and_matches_across_meshes():
    from cxxnet_tpu.models.gpt import gpt_opt_init

    def run(mesh, steps):
        params = gpt_place(gpt_init(jax.random.PRNGKey(1), CFG), mesh)
        opt = gpt_opt_init(params, mesh, "adam")
        step = make_train_step(CFG, mesh, eta=0.01, optimizer="adam")
        losses = []
        for i in range(steps):
            params, opt, loss = step(params, opt, _ids(i))
            losses.append(float(loss))
        return losses

    ref = run(make_mesh("cpu:0"), 12)
    assert ref[-1] < ref[0] * 0.5, ref
    par = run(make_mesh("cpu:0-7", model_parallel=2, seq_parallel=2), 12)
    np.testing.assert_allclose(par, ref, rtol=2e-4, atol=2e-4)


def test_make_train_step_rejects_unknown_optimizer():
    with pytest.raises(ValueError, match="optimizer"):
        make_train_step(CFG, make_mesh("cpu:0"), optimizer="rmsprop")
    from cxxnet_tpu.models.gpt import gpt_opt_init
    mesh = make_mesh("cpu:0")
    params = gpt_place(gpt_init(jax.random.PRNGKey(2), CFG), mesh)
    with pytest.raises(ValueError, match="optimizer"):
        gpt_opt_init(params, mesh, "rmsprop")


def test_remat_mode_attn_saved_matches_block():
    """The remat_mode="attn_saved" branch (_block_mlp_remat + packed
    flash residuals) must produce the same loss and gradients as the
    default whole-block remat."""
    import numpy as np
    from cxxnet_tpu.models.gpt import GPTConfig, gpt_init, gpt_loss
    from cxxnet_tpu.parallel.mesh import make_mesh
    mesh = make_mesh(devices=jax.devices()[:1])
    rs = np.random.RandomState(5)
    ids = jnp.asarray(rs.randint(0, 61, (2, 16)).astype(np.int32))
    base = dict(vocab_size=61, seq_len=16, n_layer=2, n_head=2, feat=32,
                n_microbatch=1, remat=True)
    params = gpt_init(jax.random.PRNGKey(3), GPTConfig(**base))
    out = {}
    for mode in ("block", "attn_saved"):
        cfg = GPTConfig(remat_mode=mode, **base)
        out[mode] = jax.value_and_grad(gpt_loss)(params, ids, cfg, mesh)
    np.testing.assert_allclose(out["block"][0], out["attn_saved"][0],
                               rtol=1e-6)
    for a, b in zip(jax.tree.leaves(out["block"][1]),
                    jax.tree.leaves(out["attn_saved"][1])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_remat_mode_validated():
    import numpy as np
    from cxxnet_tpu.models.gpt import GPTConfig, gpt_init, gpt_loss
    from cxxnet_tpu.parallel.mesh import make_mesh
    mesh = make_mesh(devices=jax.devices()[:1])
    cfg = GPTConfig(vocab_size=61, seq_len=16, n_layer=1, n_head=2,
                    feat=32, remat=True, remat_mode="atn_saved")
    params = gpt_init(jax.random.PRNGKey(0), cfg)
    ids = jnp.zeros((1, 16), jnp.int32)
    with pytest.raises(ValueError, match="remat_mode"):
        gpt_loss(params, ids, cfg, mesh)


def test_ring_32k_sp4_compiles():
    """Long-context multi-chip pin: the FULL train step at a 32k context,
    sequence-parallel over 4 of the 8 virtual devices (ring attention,
    8k tokens per shard), must lower and compile. Compile-only — one CPU
    execution of 32k attention would dwarf the suite; correctness of the
    ring math is pinned by the exact-equality tests at small seq
    (test_attention.py) and this proves the sharded program itself is
    valid at scale."""
    from cxxnet_tpu.models.gpt import gpt_place, gpt_opt_init
    cfg = GPTConfig(vocab_size=64, seq_len=32768, n_layer=1, n_head=2,
                    feat=64, n_microbatch=1, dtype="bfloat16", remat=True)
    mesh = make_mesh("cpu:0-7", seq_parallel=4)
    params = gpt_place(gpt_init(jax.random.PRNGKey(0), cfg), mesh)
    opt = gpt_opt_init(params, mesh, "sgd")
    step = make_train_step(cfg, mesh, eta=0.1)
    ids = jnp.zeros((2, 32768), jnp.int32)
    lowered = jax.jit(lambda p, o, x: step(p, o, x)).lower(params, opt, ids)
    compiled = lowered.compile()
    assert compiled is not None


def test_attn_layout_bhnd_matches_bnhd():
    """The head-major projection path (attn_layout="bhnd",
    _attn_core_bhnd) must be numerically identical to the token-major
    path — same math, different layout."""
    from cxxnet_tpu.models.gpt import GPTConfig, gpt_init, gpt_loss
    from cxxnet_tpu.parallel.mesh import make_mesh
    mesh = make_mesh(devices=jax.devices()[:1])
    rs = np.random.RandomState(7)
    ids = jnp.asarray(rs.randint(0, 61, (2, 16)).astype(np.int32))
    base = dict(vocab_size=61, seq_len=16, n_layer=2, n_head=2, feat=32,
                n_microbatch=1)
    params = gpt_init(jax.random.PRNGKey(4), GPTConfig(**base))
    out = {}
    for layout in ("bnhd", "bhnd"):
        cfg = GPTConfig(attn_layout=layout, **base)
        out[layout] = jax.value_and_grad(gpt_loss)(params, ids, cfg, mesh)
    np.testing.assert_allclose(out["bnhd"][0], out["bhnd"][0], rtol=1e-6)
    for a, b in zip(jax.tree.leaves(out["bnhd"][1]),
                    jax.tree.leaves(out["bhnd"][1])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_attn_layout_bhnd_remat_matches():
    """bhnd under both remat modes == bnhd without remat."""
    from cxxnet_tpu.models.gpt import GPTConfig, gpt_init, gpt_loss
    from cxxnet_tpu.parallel.mesh import make_mesh
    mesh = make_mesh(devices=jax.devices()[:1])
    rs = np.random.RandomState(8)
    ids = jnp.asarray(rs.randint(0, 61, (2, 16)).astype(np.int32))
    base = dict(vocab_size=61, seq_len=16, n_layer=2, n_head=2, feat=32,
                n_microbatch=1)
    params = gpt_init(jax.random.PRNGKey(4), GPTConfig(**base))
    ref = gpt_loss(params, ids, GPTConfig(attn_layout="bnhd", **base),
                   mesh)
    for mode in ("block", "attn_saved"):
        cfg = GPTConfig(attn_layout="bhnd", remat=True, remat_mode=mode,
                        **base)
        got = gpt_loss(params, ids, cfg, mesh)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-6)


def test_attn_layout_bhnd_tp_matches_single_device():
    """Head-major projections with tensor-parallel head shards: the
    per-shard (f, h_local, d) reshape must pick whole heads (the same
    slicing the separate-projection design guarantees for bnhd)."""
    from cxxnet_tpu.models.gpt import GPTConfig, gpt_init, gpt_place
    base = dict(vocab_size=32, seq_len=16, n_layer=2, n_head=4, feat=32,
                n_microbatch=2, attn_layout="bhnd")
    cfg = GPTConfig(**base)

    def run(mesh):
        params = gpt_place(gpt_init(jax.random.PRNGKey(0), cfg), mesh)
        mom = gpt_place(jax.tree.map(jnp.zeros_like, params), mesh)
        step = make_train_step(cfg, mesh)
        losses = []
        for i in range(3):
            params, mom, loss = step(params, mom, _ids(i))
            losses.append(float(loss))
        return losses

    ref = run(make_mesh("cpu:0"))
    par = run(make_mesh("cpu:0-7", model_parallel=2, pipeline_parallel=2))
    np.testing.assert_allclose(par, ref, rtol=2e-4, atol=2e-4)


def test_attn_layout_validated():
    from cxxnet_tpu.models.gpt import GPTConfig, gpt_init, gpt_loss
    from cxxnet_tpu.parallel.mesh import make_mesh
    mesh = make_mesh(devices=jax.devices()[:1])
    cfg = GPTConfig(vocab_size=61, seq_len=16, n_layer=1, n_head=2,
                    feat=32, attn_layout="bndh")
    params = gpt_init(jax.random.PRNGKey(0), cfg)
    ids = jnp.zeros((1, 16), jnp.int32)
    with pytest.raises(ValueError, match="attn_layout"):
        gpt_loss(params, ids, cfg, mesh)
    # bhnd composes with BOTH sequence-parallel variants since the
    # head-major ring/ulysses cores (round 3) — no layout restriction left


def test_gpt_zero3_pp2_matches_single_device():
    """ZeRO-3 (params + opt state sharded over data) composed with
    pipeline parallelism: same losses and params as the single-device
    run, and the block weights really carry a 'data' dim in their spec."""
    from cxxnet_tpu.models.gpt import (gpt_opt_init, gpt_param_shardings,
                                       gpt_place)

    def run(mesh, zero):
        params = gpt_place(gpt_init(jax.random.PRNGKey(0), CFG), mesh,
                           zero=zero)
        mom = gpt_opt_init(params, mesh, "sgd", zero=zero)
        step = make_train_step(CFG, mesh, zero=zero)
        losses = []
        for i in range(4):
            params, mom, loss = step(params, mom, _ids(i))
            losses.append(float(loss))
        return params, losses

    ref_params, ref = run(make_mesh("cpu:0"), 0)
    mesh = make_mesh("cpu:0-7", pipeline_parallel=2)
    z_params, z = run(mesh, 3)
    spec = z_params["blocks"]["w_mlp1"].sharding.spec
    assert "data" in tuple(spec), spec
    spec_m = z_params["blocks"]["w_q"].sharding.spec
    assert "data" in tuple(spec_m), spec_m
    np.testing.assert_allclose(z, ref, rtol=2e-4, atol=2e-4)
    for a, b in zip(jax.tree.leaves(jax.tree.map(np.asarray, z_params)),
                    jax.tree.leaves(jax.tree.map(np.asarray, ref_params))):
        np.testing.assert_allclose(a, b, rtol=2e-3, atol=2e-4)


def test_gpt_ulysses_matches_ring_and_single():
    """seq_parallel_mode='ulysses' (all-to-all head sharding) must train
    identically to the ring and to a single device."""
    import dataclasses
    cfg_u = dataclasses.replace(CFG, seq_parallel_mode="ulysses")

    def run(mesh, cfg):
        params = gpt_place(gpt_init(jax.random.PRNGKey(0), cfg), mesh)
        mom = gpt_place(jax.tree.map(jnp.zeros_like, params), mesh)
        step = make_train_step(cfg, mesh)
        out = []
        for i in range(4):
            params, mom, loss = step(params, mom, _ids(i))
            out.append(float(loss))
        return out

    ref = run(make_mesh("cpu:0"), CFG)
    mesh = make_mesh("cpu:0-7", seq_parallel=4)
    ring = run(mesh, CFG)
    uly = run(mesh, cfg_u)
    np.testing.assert_allclose(ring, ref, rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(uly, ref, rtol=2e-4, atol=2e-4)


def test_gpt_ulysses_head_divisibility_validated():
    import dataclasses
    cfg = dataclasses.replace(CFG, n_head=3, feat=33,
                              seq_parallel_mode="ulysses")
    mesh = make_mesh("cpu:0-7", seq_parallel=2)
    params = gpt_init(jax.random.PRNGKey(0), cfg)
    ids = jnp.zeros((2, CFG.seq_len), jnp.int32)
    with pytest.raises(ValueError, match="ulysses"):
        gpt_loss(params, ids, cfg, mesh)


def test_gpt_ulysses_composes_with_tp():
    """ulysses under sp2 x tp2: the head shards split over tp first, then
    the all-to-all re-shards the LOCAL heads over seq — losses must match
    a single device."""
    import dataclasses
    cfg_u = dataclasses.replace(CFG, seq_parallel_mode="ulysses")

    def run(mesh, cfg):
        params = gpt_place(gpt_init(jax.random.PRNGKey(0), cfg), mesh)
        mom = gpt_place(jax.tree.map(jnp.zeros_like, params), mesh)
        step = make_train_step(cfg, mesh)
        out = []
        for i in range(3):
            params, mom, loss = step(params, mom, _ids(i))
            out.append(float(loss))
        return out

    ref = run(make_mesh("cpu:0"), cfg_u)
    par = run(make_mesh("cpu:0-7", seq_parallel=2, model_parallel=2), cfg_u)
    np.testing.assert_allclose(par, ref, rtol=2e-4, atol=2e-4)


def test_attn_layout_bhnd_composes_with_ring():
    """The head-major ring core: bhnd layout + sequence parallelism must
    match the token-major ring and the single-device run."""
    import dataclasses
    cfg_b = dataclasses.replace(CFG, attn_layout="bhnd")
    cfg_n = dataclasses.replace(CFG, attn_layout="bnhd")

    def run(mesh, cfg):
        params = gpt_place(gpt_init(jax.random.PRNGKey(0), cfg), mesh)
        mom = gpt_place(jax.tree.map(jnp.zeros_like, params), mesh)
        step = make_train_step(cfg, mesh)
        out = []
        for i in range(3):
            params, mom, loss = step(params, mom, _ids(i))
            out.append(float(loss))
        return out

    ref = run(make_mesh("cpu:0"), cfg_n)
    mesh = make_mesh("cpu:0-7", seq_parallel=2, model_parallel=2)
    np.testing.assert_allclose(run(mesh, cfg_b), ref, rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(run(mesh, cfg_n), ref, rtol=2e-4, atol=2e-4)


def test_attn_layout_bhnd_composes_with_ulysses():
    import dataclasses
    cfg = dataclasses.replace(CFG, attn_layout="bhnd",
                              seq_parallel_mode="ulysses")

    def run(mesh, c):
        params = gpt_place(gpt_init(jax.random.PRNGKey(0), c), mesh)
        mom = gpt_place(jax.tree.map(jnp.zeros_like, params), mesh)
        step = make_train_step(c, mesh)
        out = []
        for i in range(3):
            params, mom, loss = step(params, mom, _ids(i))
            out.append(float(loss))
        return out

    ref = run(make_mesh("cpu:0"), CFG)
    par = run(make_mesh("cpu:0-7", seq_parallel=2), cfg)
    np.testing.assert_allclose(par, ref, rtol=2e-4, atol=2e-4)
