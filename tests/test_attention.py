"""Ring attention vs exact attention on the 8-virtual-device CPU mesh.

Differential testing in the spirit of the reference's PairTestLayer
(SURVEY §4.1): the sequence-parallel implementation must match the exact
single-device math in both values and gradients.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cxxnet_tpu.ops.attention import full_attention, ring_attention
from cxxnet_tpu.parallel.mesh import make_mesh


def _qkv(rs, b=2, n=32, h=4, d=8, dtype=np.float32):
    return tuple(jnp.asarray(rs.randn(b, n, h, d).astype(dtype)) for _ in range(3))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("seq_parallel", [1, 4, 8])
def test_ring_matches_full(causal, seq_parallel):
    rs = np.random.RandomState(0)
    q, k, v = _qkv(rs)
    mesh = make_mesh("cpu:0-7", seq_parallel=seq_parallel)
    ref = full_attention(q, k, v, causal=causal)
    out = jax.jit(lambda a, b_, c: ring_attention(
        a, b_, c, mesh, causal=causal))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_gradients_match_full(causal):
    rs = np.random.RandomState(1)
    q, k, v = _qkv(rs, n=16)
    mesh = make_mesh("cpu:0-7", seq_parallel=4)

    def loss_full(q, k, v):
        return (full_attention(q, k, v, causal=causal) ** 2).sum()

    def loss_ring(q, k, v):
        return (ring_attention(q, k, v, mesh, causal=causal) ** 2).sum()

    g_ref = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    g_out = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_out, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_ring_with_data_parallel_batch():
    """Composed dp x sp mesh: batch sharded over data, seq over seq."""
    rs = np.random.RandomState(2)
    q, k, v = _qkv(rs, b=4, n=16)
    mesh = make_mesh("cpu:0-7", seq_parallel=4)   # data=2, seq=4
    assert mesh.shape["data"] == 2
    ref = full_attention(q, k, v, causal=True)
    out = jax.jit(lambda a, b_, c: ring_attention(
        a, b_, c, mesh, causal=True))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_causal_first_token_attends_only_itself():
    rs = np.random.RandomState(3)
    q, k, v = _qkv(rs, b=1, n=8, h=1, d=4)
    out = full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out[0, 0]), np.asarray(v[0, 0]),
                               rtol=1e-6)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_chunk_kernels_match_full(causal, monkeypatch):
    """The Pallas chunk-kernel path inside the ring (forward lse-merge +
    blockwise backward with the global lse) must match exact attention.
    Interpret mode + lowered threshold so the path runs on CPU."""
    import cxxnet_tpu.ops.attention as att
    import cxxnet_tpu.ops.pallas_kernels as pk

    monkeypatch.setattr(pk, "_INTERPRET", True)
    monkeypatch.setattr(att, "_RING_PALLAS_MIN", 8)
    monkeypatch.setattr(att, "_RING_PALLAS_ALIGN", 8)

    rs = np.random.RandomState(3)
    q, k, v = _qkv(rs, n=32, d=16)
    mesh = make_mesh("cpu:0-7", seq_parallel=4)
    assert att._ring_chunk_kernels(32 // 4)

    ref = full_attention(q, k, v, causal=causal)
    out = jax.jit(lambda a, b_, c: ring_attention(
        a, b_, c, mesh, causal=causal))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    g_ref = jax.grad(lambda a, b_, c: (
        full_attention(a, b_, c, causal=causal) ** 2).sum(),
        (0, 1, 2))(q, k, v)
    g_out = jax.jit(jax.grad(lambda a, b_, c: (
        ring_attention(a, b_, c, mesh, causal=causal) ** 2).sum(),
        (0, 1, 2)))(q, k, v)
    for a, b in zip(g_out, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_attention_matches_torch_sdpa():
    """Cross-framework oracle (PairTest-with-Caffe spirit, SURVEY §4.2):
    our exact attention and the ring implementation vs torch's
    scaled_dot_product_attention."""
    torch = pytest.importorskip("torch")
    rs = np.random.RandomState(5)
    b, n, h, d = 2, 32, 4, 16
    q, k, v = (rs.randn(b, n, h, d).astype(np.float32) for _ in range(3))

    tq, tk, tv = (torch.from_numpy(x.transpose(0, 2, 1, 3)) for x in (q, k, v))
    ref = torch.nn.functional.scaled_dot_product_attention(
        tq, tk, tv, is_causal=True).numpy().transpose(0, 2, 1, 3)

    ours = np.asarray(full_attention(jnp.asarray(q), jnp.asarray(k),
                                     jnp.asarray(v), causal=True))
    np.testing.assert_allclose(ours, ref, rtol=2e-5, atol=2e-5)

    mesh = make_mesh("cpu:0-7", seq_parallel=4)
    ring = np.asarray(jax.jit(lambda a, b_, c: ring_attention(
        a, b_, c, mesh, causal=True))(jnp.asarray(q), jnp.asarray(k),
                                      jnp.asarray(v)))
    np.testing.assert_allclose(ring, ref, rtol=2e-5, atol=2e-5)


# ------------------------------------------------------------- ulysses
@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("seq_parallel", [1, 2, 4])
def test_ulysses_matches_full(causal, seq_parallel):
    from cxxnet_tpu.ops.attention import ulysses_attention
    rs = np.random.RandomState(10)
    q, k, v = _qkv(rs)                       # h=4 divides every sp here
    mesh = make_mesh("cpu:0-7", seq_parallel=seq_parallel)
    ref = full_attention(q, k, v, causal=causal)
    out = jax.jit(lambda a, b_, c: ulysses_attention(
        a, b_, c, mesh, causal=causal))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_gradients_match_full(causal):
    from cxxnet_tpu.ops.attention import ulysses_attention
    rs = np.random.RandomState(11)
    q, k, v = _qkv(rs, n=16)
    mesh = make_mesh("cpu:0-7", seq_parallel=4)

    def loss_full(q, k, v):
        return (full_attention(q, k, v, causal=causal) ** 2).sum()

    def loss_uly(q, k, v):
        return (ulysses_attention(q, k, v, mesh, causal=causal) ** 2).sum()

    g_ref = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    g_uly = jax.jit(jax.grad(loss_uly, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_uly, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_ulysses_matches_ring():
    from cxxnet_tpu.ops.attention import ulysses_attention
    rs = np.random.RandomState(12)
    q, k, v = _qkv(rs)
    mesh = make_mesh("cpu:0-7", seq_parallel=4)
    a = jax.jit(lambda x, y, z: ring_attention(x, y, z, mesh,
                                               causal=True))(q, k, v)
    b = jax.jit(lambda x, y, z: ulysses_attention(x, y, z, mesh,
                                                  causal=True))(q, k, v)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_head_divisibility_validated():
    from cxxnet_tpu.ops.attention import ulysses_attention
    rs = np.random.RandomState(13)
    q, k, v = _qkv(rs, h=3)                  # 3 heads over sp4: invalid
    mesh = make_mesh("cpu:0-7", seq_parallel=4)
    with pytest.raises(ValueError, match="heads"):
        ulysses_attention(q, k, v, mesh, causal=True)
