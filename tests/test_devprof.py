"""Device & compiler observatory tests (obs/devprof.py,
doc/observability.md "Device & compiler metrics").

Pinned here: the cost table covers all seven hot programs on CPU (the
four trainer steps + the three serve programs, plus the legacy
prefill), the device-memory ledger reconciles predicted pool sizes
against live arrays, the live sampler's cadence is respected (no
per-tick blocking), the cost_analysis-unavailable path degrades to a
finding instead of a crash, compile-time accounting attributes compile
events to program labels, and the ``cxn_prof --diff`` bench gate
passes identical snapshots while flagging an injected regression.
"""

import dataclasses
import json
import os
import sys

import jax
import numpy as np
import pytest

from cxxnet_tpu.models.gpt import GPTConfig, gpt_init
from cxxnet_tpu.obs import devprof
from cxxnet_tpu.obs.metrics import BYTES_BUCKETS, Registry, TIME_BUCKETS
from cxxnet_tpu.serve import InferenceServer
from cxxnet_tpu.serve.engine import DecodeEngine

CFG = GPTConfig(vocab_size=32, seq_len=32, n_layer=2, n_head=2, feat=16,
                n_microbatch=1, dtype="float32")
PARAMS = gpt_init(jax.random.PRNGKey(3), CFG)

TRAIN_PROGRAMS = ("net_update", "net_accum", "net_apply", "net_forward")
SERVE_PROGRAMS = ("serve_prefill_chunk", "serve_verify_chunk",
                  "serve_tick")

@pytest.fixture(scope="module")
def gpt_net():
    """A tiny config-DSL GPT Net (the gpt_lm_config surface), shared
    across the module — building one per test would recompile the
    four steps each time."""
    from cxxnet_tpu.models import gpt_lm_config
    from cxxnet_tpu.nnet.net import Net
    from cxxnet_tpu.utils.config import tokenize
    cfg = gpt_lm_config(seq_len=16, vocab_size=32, feat=16, nhead=2,
                        nblock=2, batch_size=8, precision="float32",
                        updater="sgd", eta=0.1)
    net = Net(tokenize(cfg))
    net.init_model()
    return net


# ---------------------------------------------------------------- cost table
def test_cost_table_covers_trainer_steps(gpt_net):
    table = devprof.profile_net(gpt_net, time_reps=1)
    assert set(TRAIN_PROGRAMS) <= set(table.names())
    for name in TRAIN_PROGRAMS:
        pc = table.get(name)
        assert pc.available, pc.note
        assert pc.flops > 0
        assert pc.bytes_accessed > 0
        assert pc.peak_bytes > 0
        assert pc.compile_s >= 0
        assert pc.measured_s > 0            # timed on CPU
        assert pc.mfu(pc.measured_s, table.peaks) > 0
    # roofline renders every row with a measured column
    text = table.format_roofline()
    for name in TRAIN_PROGRAMS:
        assert name in text
    assert "peaks:" in text


def test_cost_table_covers_serve_programs():
    eng = DecodeEngine(CFG, PARAMS, slots=2, prefill_chunk=8, spec_len=2)
    table = devprof.profile_engine(eng, time_reps=1)
    assert set(SERVE_PROGRAMS) <= set(table.names())
    assert "serve_prefill" in table.names()     # legacy admit rides along
    for name in SERVE_PROGRAMS:
        pc = table.get(name)
        assert pc.available, pc.note
        assert pc.flops > 0 and pc.bytes_accessed > 0
        assert pc.peak_bytes > 0
        assert pc.measured_s > 0
    eng.close()


def test_cost_extraction_cache_reuses_rows():
    eng = DecodeEngine(CFG, PARAMS, slots=2, prefill_chunk=8)
    t1 = devprof.profile_engine(eng)
    t2 = devprof.profile_engine(eng)        # same signatures -> cached
    for name in t1.names():
        assert t2.get(name).flops == t1.get(name).flops
    # cached rows are copies: mutating one table cannot corrupt the
    # process-wide cache another server will read
    t1.get("serve_tick").measured_s = 123.0
    assert devprof.profile_engine(eng).get("serve_tick").measured_s != 123.0
    eng.close()


def test_cost_cache_keyed_by_program_identity():
    # two DIFFERENT programs sharing a label and identical arg shapes
    # (the remat-twin / same-shaped-config hazard) must not alias one
    # cached row — program identity is the jit object itself
    import jax.numpy as jnp
    f1 = jax.jit(lambda x: x + 1)
    f2 = jax.jit(lambda x: (x * x).sum() + x)   # different program
    args = (jax.ShapeDtypeStruct((4, 4), jnp.float32),)
    pc1, _ = devprof.extract_program(f1, args, "twin")
    pc2, _ = devprof.extract_program(f2, args, "twin")
    assert pc1.flops != pc2.flops
    # and the same (fn, args) pair still caches
    pc1b, compiled = devprof.extract_program(f1, args, "twin")
    assert compiled is None and pc1b.flops == pc1.flops


def test_publish_registry_gauges():
    eng = DecodeEngine(CFG, PARAMS, slots=2, prefill_chunk=8)
    reg = Registry()
    devprof.profile_engine(eng, registry=reg)
    snap = reg.snapshot()
    assert snap['cxn_program_flops{fn="serve_tick"}'] > 0
    assert snap['cxn_program_peak_bytes{fn="serve_tick"}'] > 0
    assert snap['cxn_program_bytes_accessed{fn="serve_prefill_chunk"}'] > 0
    eng.close()


# ------------------------------------------------------- unavailable backend
class _DeadCompiled:
    def cost_analysis(self):
        raise NotImplementedError("no cost analysis on this backend")

    def memory_analysis(self):
        raise NotImplementedError("no memory analysis on this backend")


def test_unavailable_analyses_degrade_to_note_not_crash():
    pc = devprof._cost_from_compiled("net_update", _DeadCompiled())
    assert not pc.available
    assert "unavailable on this backend" in pc.note
    # the roofline table renders the note instead of fake numbers
    table = devprof.CostTable()
    table.add(pc)
    text = table.format_roofline()
    assert "unavailable on this backend" in text
    # and publish() registers nothing for the unavailable program
    reg = Registry()
    table.publish(reg)
    snap = reg.snapshot()
    assert not any(k.startswith("cxn_program_flops") for k in snap)


def test_partial_availability_keeps_memory_side():
    class _HalfDead:
        def cost_analysis(self):
            raise NotImplementedError

        def memory_analysis(self):
            return dataclasses.make_dataclass("M", [
                ("argument_size_in_bytes", int), ("output_size_in_bytes",
                 int), ("temp_size_in_bytes", int),
                ("alias_size_in_bytes", int),
                ("generated_code_size_in_bytes", int)])(100, 50, 25, 0, 1)

    pc = devprof._cost_from_compiled("x", _HalfDead())
    assert pc.available                 # memory side still useful
    assert pc.peak_bytes == 175
    assert pc.flops == -1.0
    assert "cost_analysis unavailable" in pc.note


# ------------------------------------------------------------------- ledger
def test_ledger_reconciles_for_small_serve_config():
    """Paged server (the default): `kv_blocks` is the whole block pool
    (trie-resident blocks live INSIDE it — no separate prefix pool, no
    double count) and `swap_host` is a HOST pool: published as a gauge
    but excluded from the device reconciliation."""
    import gc
    gc.collect()
    srv = InferenceServer(CFG, PARAMS, slots=2, queue=8, prefill_chunk=8)
    try:
        h = srv.submit(np.arange(6, dtype=np.int32) % 32, max_tokens=8)
        assert srv.result(h).status == "ok"
        rec = srv.metrics()["device_bytes"]
        eng = srv._engine
        # the pools' predictions are exact for what they model
        assert rec["pools"]["kv_blocks"] == eng.cache_bytes()
        assert rec["pools"]["params"] == devprof.tree_nbytes(
            (eng._blocks, eng._outer))
        assert rec["pools"]["swap_host"] == 0       # nothing preempted
        assert "prefix_cache" not in rec["pools"]   # inside kv_blocks
        # accounted = DEVICE pools only (swap_host is host memory)
        assert rec["accounted"] == pytest.approx(
            sum(v for p, v in rec["pools"].items() if p != "swap_host"))
        # the measured live total covers at least the accounted pools
        # (module-level PARAMS etc. land in `unaccounted`, never below)
        assert rec["live_total"] >= rec["accounted"] * 0.99
        assert rec["live_total"] == rec["accounted"] + rec["unaccounted"]
        # exposed as cxn_device_bytes{pool=} gauges
        snap = srv.registry.snapshot()
        assert snap['cxn_device_bytes{pool="kv_blocks"}'] == \
            eng.cache_bytes()
        assert snap['cxn_device_bytes{pool="live_total"}'] >= \
            rec["accounted"] * 0.99
    finally:
        srv.shutdown()
    # post-shutdown the frozen gauges report the drained state without
    # evaluating (or pinning) the dead engine
    snap = srv.registry.snapshot()
    assert snap['cxn_device_bytes{pool="kv_blocks"}'] == 0


def test_ledger_reconciles_for_dense_serve_config():
    """paged=False keeps the dense pools: kv_slots + the prefix trie's
    own (copied) bytes."""
    import gc
    gc.collect()
    srv = InferenceServer(CFG, PARAMS, slots=2, queue=8, prefill_chunk=8,
                          paged=False)
    try:
        h = srv.submit(np.arange(6, dtype=np.int32) % 32, max_tokens=8)
        assert srv.result(h).status == "ok"
        rec = srv.metrics()["device_bytes"]
        eng = srv._engine
        assert rec["pools"]["kv_slots"] == eng.cache_bytes()
        assert rec["pools"]["prefix_cache"] == srv._prefix.nbytes
        assert rec["accounted"] == pytest.approx(
            sum(rec["pools"].values()))
        assert rec["live_total"] >= rec["accounted"] * 0.99
    finally:
        srv.shutdown()
    snap = srv.registry.snapshot()
    assert snap['cxn_device_bytes{pool="kv_slots"}'] == 0


# ------------------------------------------------------------- live sampler
def test_sampler_cadence_respected():
    reg = Registry()
    s = devprof.LiveSampler(reg, cadence=4)
    starts = [s.begin("serve_tick") for _ in range(11)]
    # executions 4 and 8 sample; everything else returns None untimed
    assert [t is not None for t in starts] == \
        [i % 4 == 0 for i in range(1, 12)]
    for t in (t for t in starts if t is not None):
        s.end("serve_tick", t)
    assert s.samples["serve_tick"] == 2
    assert reg.snapshot()['cxn_prof_samples_total{fn="serve_tick"}'] == 2


def test_sampler_cadence_zero_never_samples():
    s = devprof.LiveSampler(Registry(), cadence=0)
    assert all(s.begin("serve_tick") is None for _ in range(10))


def test_server_prof_every_samples_and_publishes_mfu():
    srv = InferenceServer(CFG, PARAMS, slots=2, queue=8, prefill_chunk=8,
                          prof_every=3)
    try:
        h = srv.submit(np.arange(5, dtype=np.int32) % 32, max_tokens=12)
        assert srv.result(h).status == "ok"
        sampler = srv._prof_sampler
        assert sampler is not None
        ticks = sampler.executions("serve_tick")
        assert ticks >= 3
        assert sampler.samples["serve_tick"] == ticks // 3
        snap = srv.registry.snapshot()
        assert snap['cxn_mfu{fn="serve_tick"}'] > 0
        assert snap['cxn_achieved_bw_frac{fn="serve_tick"}'] > 0
        h_ = snap['cxn_program_seconds{fn="serve_tick"}']
        assert h_["count"] == ticks // 3
    finally:
        srv.shutdown()


def test_server_prof_off_is_default_and_untouched():
    srv = InferenceServer(CFG, PARAMS, slots=2, queue=8, prefill_chunk=8)
    try:
        h = srv.submit(np.arange(5, dtype=np.int32) % 32, max_tokens=8)
        assert srv.result(h).status == "ok"
        assert srv._prof_sampler is None
        assert srv._engine._prof is None
        snap = srv.registry.snapshot()
        assert not any(k.startswith("cxn_program_seconds") for k in snap)
        assert not any(k.startswith("cxn_mfu") for k in snap)
    finally:
        srv.shutdown()


def test_sampler_drops_compile_contaminated_window():
    import jax.numpy as jnp
    reg = Registry()
    watch = devprof.compile_watch()
    watch.add_sink(reg)             # installs the monitoring listener
    try:
        s = devprof.LiveSampler(reg, cadence=1)
        tok = s.begin("serve_tick")
        # a fresh-shape compile lands INSIDE the timed window — the
        # sample must be discarded, not recorded as a 1000x outlier
        jax.jit(lambda x: x - 2)(jnp.zeros((23, 3)))
        s.end("serve_tick", tok)
        assert s.dropped.get("serve_tick") == 1
        assert "serve_tick" not in s.samples
        snap = reg.snapshot()
        assert snap['cxn_prof_samples_dropped_total{fn="serve_tick"}'] == 1
        # a clean window still records
        tok = s.begin("serve_tick")
        s.end("serve_tick", tok)
        assert s.samples["serve_tick"] == 1
    finally:
        watch.remove_sink(reg)


def test_net_pool_gauges_release_dropped_net():
    import gc
    from cxxnet_tpu.models import gpt_lm_config
    from cxxnet_tpu.nnet.net import Net
    from cxxnet_tpu.utils.config import tokenize
    reg = Registry()
    net = Net(tokenize(gpt_lm_config(seq_len=16, vocab_size=32, feat=16,
                                     nhead=2, nblock=2, batch_size=8,
                                     precision="float32", updater="sgd",
                                     eta=0.1)))
    net.init_model()
    ledger = devprof.register_net_pools(net, registry=reg)
    assert ledger.pool_bytes("params") > 0
    assert ledger.pool_bytes("opt_state") > 0
    del net
    gc.collect()
    # the registry must not pin a dropped net's device buffers: the
    # weakref'd pools read 0 instead of keeping params/opt_state alive
    assert ledger.pool_bytes("params") == 0
    assert ledger.pool_bytes("opt_state") == 0


# -------------------------------------------------------- compile accounting
def test_compile_watch_attributes_to_labels():
    import jax.numpy as jnp
    reg = Registry()
    watch = devprof.compile_watch()
    watch.add_sink(reg)
    try:
        with devprof.compile_attribution("test_program"):
            # a fresh shape forces a real compile under the label
            jax.jit(lambda x: x * 3 + 1)(jnp.zeros((17, 13)))
        snap = reg.snapshot()
        assert snap['cxn_compile_seconds{fn="test_program"}'] > 0
        assert watch.totals.get("test_program", 0) > 0
    finally:
        watch.remove_sink(reg)
    # after removal further compiles leave this registry untouched
    before = reg.snapshot()['cxn_compile_seconds{fn="test_program"}']
    with devprof.compile_attribution("test_program"):
        jax.jit(lambda x: x * 5)(jnp.zeros((19, 7)))
    assert reg.snapshot()['cxn_compile_seconds{fn="test_program"}'] \
        == before


def test_server_compile_seconds_per_program():
    srv = InferenceServer(CFG, PARAMS, slots=3, queue=8, prefill_chunk=16)
    try:
        h = srv.submit(np.arange(5, dtype=np.int32) % 32, max_tokens=6)
        assert srv.result(h).status == "ok"
        snap = srv.registry.snapshot()
        # the engine's real program compiles land under their labels
        # (a shared-jit-cache hit from an earlier test reads 0 — the
        # series still exists, pre-touched by the sink)
        assert 'cxn_compile_seconds{fn="serve_tick"}' in snap \
            or any(k.startswith("cxn_compile_seconds") for k in snap)
    finally:
        srv.shutdown()


# ------------------------------------------------------------- task=prof CLI
def test_task_prof_reports_all_programs(tmp_path, capfd):
    from cxxnet_tpu.cli import main as cli_main
    conf = tmp_path / "prof.conf"
    from cxxnet_tpu.models import gpt_lm_config
    conf.write_text(gpt_lm_config(seq_len=16, vocab_size=32, feat=16,
                                  nhead=2, nblock=2, batch_size=8,
                                  precision="float32", updater="sgd",
                                  eta=0.1))
    rc = cli_main([str(conf), "task=prof", "prof_reps=1",
                   "serve_prefill_chunk=8", "silent=1"])
    out = capfd.readouterr().out
    assert rc == 0
    for name in TRAIN_PROGRAMS + SERVE_PROGRAMS:
        assert name in out, "roofline table missing %s" % name
    assert "device memory:" in out
    assert "compile seconds:" in out


def test_wrapper_profile(gpt_net):
    # the wrapper surface shares profile_net, so cached rows make this
    # cheap; the returned table is the same renderer task=prof prints
    import cxxnet_tpu.wrapper as wrapper
    w = wrapper.Net.__new__(wrapper.Net)
    w._net = gpt_net
    table = w.profile(time_reps=0)
    assert set(TRAIN_PROGRAMS) <= set(table.names())


# ----------------------------------------------------------- cxn_prof --diff
def _write_bench(path, cells):
    with open(path, "w") as f:
        for metric, value, unit, extra in cells:
            rec = {"metric": metric, "value": value, "unit": unit,
                   "vs_baseline": None}
            rec.update(extra)
            f.write(json.dumps(rec) + "\n")


_BASE_CELLS = [
    ("gpt_train_tokens_per_sec", 64000.0, "tokens/sec", {}),
    ("gpt_decode_ms_per_token", 0.40, "ms/token", {}),
    ("moe_dispatch_tokens_per_sec", 900000.0, "tokens/sec",
     {"band": [880000.0, 910000.0]}),
]


def _run_diff(old, new, *extra):
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from tools.cxn_prof import main as prof_main
    return prof_main(["--diff", str(old), str(new)] + list(extra))


def test_prof_diff_identical_snapshots_pass(tmp_path, capfd):
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    _write_bench(a, _BASE_CELLS)
    _write_bench(b, _BASE_CELLS)
    assert _run_diff(a, b) == 0
    assert "no regressions" in capfd.readouterr().out


def test_prof_diff_flags_injected_regression(tmp_path, capfd):
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    _write_bench(a, _BASE_CELLS)
    bad = [(m, v * 0.5 if m == "gpt_train_tokens_per_sec" else v, u, e)
           for m, v, u, e in _BASE_CELLS]
    _write_bench(b, bad)
    assert _run_diff(a, b) == 1
    out = capfd.readouterr().out
    assert "REGRESSED" in out
    assert "gpt_train_tokens_per_sec" in out


def test_prof_diff_direction_follows_unit(tmp_path, capfd):
    # a LOWER ms/token is an improvement, never a regression; a HIGHER
    # one regresses
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    _write_bench(a, _BASE_CELLS)
    better = [(m, v * 0.5 if m == "gpt_decode_ms_per_token" else v, u, e)
              for m, v, u, e in _BASE_CELLS]
    _write_bench(b, better)
    assert _run_diff(a, b) == 0
    assert "improved" in capfd.readouterr().out


def test_prof_diff_band_widens_tolerance(tmp_path, capfd):
    # the MoE cell recorded a ~3% best-of band; a 12% drop is inside
    # its widened cell tolerance (15% floor) while the same drop on an
    # unbanded 10%-tol cell would regress — pin the band path by
    # overriding the cell floor down
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    _write_bench(a, _BASE_CELLS)
    moved = [(m, v * 0.89 if m == "moe_dispatch_tokens_per_sec" else v,
              u, e) for m, v, u, e in _BASE_CELLS]
    _write_bench(b, moved)
    assert _run_diff(a, b, "--cell-tol",
                     "moe_dispatch_tokens_per_sec=0.10") == 0
    capfd.readouterr()


def test_prof_diff_reads_driver_wrapper_format(tmp_path, capfd):
    # BENCH_rXX.json as the driver records it: one wrapper object whose
    # `tail` embeds the metric lines
    inner = "\n".join(json.dumps({"metric": m, "value": v, "unit": u})
                      for m, v, u, _ in _BASE_CELLS)
    a = tmp_path / "BENCH_rXX.json"
    a.write_text(json.dumps({"n": 1, "tail": "noise\n" + inner + "\n"}))
    b = tmp_path / "b.json"
    _write_bench(b, _BASE_CELLS)
    assert _run_diff(a, b) == 0
    capfd.readouterr()


# ------------------------------------------------------------ hw peaks/misc
def test_hw_peaks_sources_and_overrides(monkeypatch):
    p = devprof.hw_peaks()
    assert p.flops > 0 and p.bytes_per_s > 0    # CPU falls back to v5e
    assert "assumed" in p.source or "device_kind" in p.source
    assert devprof.hw_peaks(flops=1e12, bytes_per_s=1e9) == \
        (1e12, 1e9, "explicit")
    monkeypatch.setenv("CXN_PEAK_FLOPS", "2e12")
    monkeypatch.setenv("CXN_PEAK_BW", "3e9")
    env = devprof.hw_peaks()
    assert env.flops == 2e12 and env.bytes_per_s == 3e9


def test_bytes_buckets_geometry_and_merge():
    from cxxnet_tpu.obs.metrics import Histogram
    # TIME_BUCKETS tops out far below GiB scale — a bytes histogram
    # there lands everything in +Inf; BYTES_BUCKETS spreads it
    assert TIME_BUCKETS[-1] < 1e4 < BYTES_BUCKETS[-1]
    h = Histogram(buckets=BYTES_BUCKETS)
    for v in (512.0, 1 << 20, 1 << 30):
        h.observe(v)
    counts = h.counts()
    assert counts[-1] == 0                  # nothing overflowed
    assert sum(1 for c in counts if c) == 3  # three distinct buckets
    # the merge property holds for the new geometry exactly as pinned
    # for TIME_BUCKETS (obs/metrics.py module contract)
    a, b = Histogram(buckets=BYTES_BUCKETS), Histogram(
        buckets=BYTES_BUCKETS)
    combined = Histogram(buckets=BYTES_BUCKETS)
    for i, v in enumerate([300.0, 4096.0, 1 << 22, 1 << 33, 7e11]):
        (a if i % 2 else b).observe(v)
        combined.observe(v)
    a.merge(b)
    assert a.counts() == combined.counts()
    assert a.sum == combined.sum and a.count == combined.count
    with pytest.raises(ValueError):
        a.merge(Histogram(buckets=TIME_BUCKETS))


def test_labeled_per_child_callbacks_and_rebind():
    reg = Registry()
    fam = reg.gauge("t_pool_bytes", "x", labelnames=("pool",))
    box = {"v": 7.0}
    fam.labels("a", fn=lambda: box["v"])
    fam.labels("b", fn=lambda: 2 * box["v"])
    snap = reg.snapshot()
    assert snap['t_pool_bytes{pool="a"}'] == 7.0
    assert snap['t_pool_bytes{pool="b"}'] == 14.0
    # rebinding a child replaces its provider (latest wins)
    fam.labels("a", fn=lambda: 100.0)
    assert reg.snapshot()['t_pool_bytes{pool="a"}'] == 100.0
    # histograms refuse per-child callbacks
    hfam = reg.histogram("t_h", "x", labelnames=("k",))
    with pytest.raises(ValueError):
        hfam.labels("a", fn=lambda: 1.0)
