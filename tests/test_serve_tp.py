"""Tensor-parallel serving (gather-form TP, serve/engine.py module
docstring; doc/serving.md "Sharded & replicated serving").

The acceptance matrix on the forced multi-device CPU mesh
(tests/conftest.py): TP-sharded decode is BIT-IDENTICAL to the
single-device engine and to solo ``gpt_decode`` — greedy AND sampled,
since the gather form never splits a contraction — across chunked
prefill, prefix hits, recycled slots, speculative decoding, and paged
preemption/swap; the step audit sees the head-axis KV pool shardings
and zero all-reduces with donation aliasing intact; RecompileGuard
signatures carry the mesh shape; and the fused paged-attention kernel
runs UNDER TP through the shard_map wrap (the support gate evaluates
the LOCAL head count), serving bit-identical to the single-device
fused engine, with ``CXN_FUSED_ATTN=0`` still arming the gather
fallback as a no-op on the token stream.
"""

import jax
import numpy as np
import pytest

from cxxnet_tpu.models.gpt import GPTConfig, gpt_decode, gpt_init
from cxxnet_tpu.parallel.mesh import make_mesh
from cxxnet_tpu.serve import DecodeEngine, InferenceServer
from cxxnet_tpu.serve.engine import (serve_kv_sharding,
                                     serve_param_shardings,
                                     serve_tp_size)

CFG = GPTConfig(vocab_size=32, seq_len=48, n_layer=2, n_head=2, feat=16,
                n_microbatch=1)
PARAMS = gpt_init(jax.random.PRNGKey(5), CFG)


def _mesh(tp=2):
    return make_mesh(devices=jax.devices()[:tp], model_parallel=tp)


def _prompt(rs, n):
    return rs.randint(0, CFG.vocab_size, (n,)).astype(np.int32)


def _ref(prompt, max_new, temperature=0.0, seed=0, **kw):
    rng = jax.random.PRNGKey(seed) if temperature > 0 else None
    return np.asarray(gpt_decode(PARAMS, prompt[None], max_new, CFG,
                                 temperature=temperature, rng=rng,
                                 **kw))[0]


def _serve_all(srv, jobs):
    """jobs: [(prompt, max_tokens, overrides)] -> token arrays, order
    preserved; every request must finish ok."""
    hs = [srv.submit(p, max_tokens=m, **ov) for p, m, ov in jobs]
    out = []
    for h in hs:
        r = srv.result(h, timeout=300)
        assert r.status == "ok", (r.status, r.error)
        out.append(r.tokens)
    return out


# ------------------------------------------------------------ validation
def test_tp_requires_divisible_heads_and_chunking():
    cfg3 = GPTConfig(vocab_size=32, seq_len=32, n_layer=1, n_head=3,
                     feat=18, n_microbatch=1)
    with pytest.raises(ValueError, match="divisible by the model-axis"):
        DecodeEngine(cfg3, gpt_init(jax.random.PRNGKey(0), cfg3), 2,
                     prefill_chunk=4, mesh=_mesh())
    with pytest.raises(ValueError, match="chunked prefill"):
        DecodeEngine(CFG, PARAMS, 2, prefill_chunk=0, mesh=_mesh())
    # a mesh without a >1 model axis is plain single-device serving
    eng = DecodeEngine(CFG, PARAMS, 2, prefill_chunk=4, abstract=True,
                       mesh=make_mesh(devices=jax.devices()[:1]))
    assert eng.tp == 1 and eng.mesh is None
    assert serve_tp_size(None) == 1


def test_server_tp_needs_enough_devices():
    with pytest.raises(ValueError, match="devices"):
        InferenceServer(CFG, PARAMS, slots=2, tp=99)


# ------------------------------------------------------- token identity
def test_tp_paged_bit_identical_mixed_traffic():
    """TP=2 paged serving: greedy AND sampled streams equal solo
    gpt_decode across mixed lengths, shared-prefix hits, and recycled
    slots (more requests than slots). (tp=1 == the same oracle is
    test_serve.py's pin, so tp=2 == tp=1 follows.)"""
    rs = np.random.RandomState(0)
    shared = _prompt(rs, 9)
    jobs = []
    for n in (6, 11, 17, 5):
        jobs.append((_prompt(rs, n), 6, {}))
    jobs.append((np.concatenate([shared, _prompt(rs, 4)]), 5, {}))
    jobs.append((np.concatenate([shared, _prompt(rs, 2)]), 5, {}))
    # sampled rows: the gather form keeps logits bit-identical, so even
    # sampled tokens match the offline path exactly
    jobs.append((_prompt(rs, 8), 6,
                 dict(temperature=0.9, top_k=8, seed=3)))
    refs = [_ref(p, m, **ov) for p, m, ov in jobs]
    with InferenceServer(CFG, PARAMS, slots=2, queue=16,
                         prefill_chunk=4, tp=2) as srv:
        assert srv.tp == 2
        got = _serve_all(srv, jobs)
    for g, r in zip(got, refs):
        assert np.array_equal(g, r), (g, r)


def test_tp_dense_bit_identical():
    rs = np.random.RandomState(1)
    jobs = [(_prompt(rs, n), 6, {}) for n in (6, 11, 3)]
    refs = [_ref(p, m) for p, m, _ in jobs]
    with InferenceServer(CFG, PARAMS, slots=2, queue=8, prefill_chunk=4,
                         paged=False, tp=2) as srv:
        got = _serve_all(srv, jobs)
    for g, r in zip(got, refs):
        assert np.array_equal(g, r)


def test_tp_speculative_greedy_identical():
    rs = np.random.RandomState(2)
    # repetitive suffixes so the n-gram drafter actually proposes
    base = _prompt(rs, 5)
    jobs = [(np.concatenate([base, base, base[:2]]), 8, {}),
            (_prompt(rs, 7), 8, {})]
    refs = [_ref(p, m) for p, m, _ in jobs]
    with InferenceServer(CFG, PARAMS, slots=2, queue=8, prefill_chunk=4,
                         spec_mode="ngram", spec_len=3, tp=2) as srv:
        got = _serve_all(srv, jobs)
    for g, r in zip(got, refs):
        assert np.array_equal(g, r)


def test_tp_preempt_swap_resume_identity():
    """A pool small enough to force preemption + host swap under TP:
    the swap gather/scatter programs run over the head-sharded pool and
    the resumed rows stay bit-exact."""
    rs = np.random.RandomState(3)
    jobs = [(_prompt(rs, 12), 10, {}) for _ in range(4)]
    refs = [_ref(p, m) for p, m, _ in jobs]
    with InferenceServer(CFG, PARAMS, slots=4, queue=8, prefill_chunk=4,
                         num_blocks=14, tp=2, degrade=False) as srv:
        got = _serve_all(srv, jobs)
        m = srv.metrics()["paged"]
    for g, r in zip(got, refs):
        assert np.array_equal(g, r)
    # the tiny pool really exercised preemption + swap (14 blocks hold
    # ~2 of the 4 rows; measured 2 swap round trips at this geometry)
    assert m["swaps_out"] > 0 and m["swaps_in"] > 0


# ----------------------------------------------------------- step audit
def test_tp_audit_shardings_collectives_donation():
    """The compiled-step audit over the TP engine: abstract inputs
    carry the REAL mesh shardings (the head-axis KV pool spec shows up
    in the step info), donation aliasing survives partitioning, the
    collective count fits a pinned budget, and — the bit-identity
    invariant made structural — there are ZERO all-reduces: the gather
    form moves data, it never re-associates a contraction."""
    from cxxnet_tpu.analysis.step_audit import (audit_serve_engine,
                                                format_step_info)
    mesh = _mesh()
    eng = DecodeEngine(CFG, PARAMS, 2, prefill_chunk=4, abstract=True,
                       num_blocks=30, spec_len=2, mesh=mesh)
    report, infos = audit_serve_engine(eng, donate=True,
                                       collective_budget=8 * CFG.n_layer)
    assert report.ok(), report.format()
    assert {i["label"] for i in infos} == {
        "serve_prefill_chunk", "serve_verify_chunk", "serve_tick"}
    kv_spec = str(serve_kv_sharding(mesh).spec)
    for info in infos:
        assert kv_spec in info["shardings"], info
        assert info["collectives"]["all-reduce"] == 0, info
        assert info["collectives"]["all-gather"] > 0, info
        assert info["aliased"] == info["donated"] == 2, info
        assert "sharded[" in format_step_info(info)
    # an unsharded engine's audit reports no shardings (no regression
    # in the single-device step table)
    eng1 = DecodeEngine(CFG, PARAMS, 2, prefill_chunk=4, abstract=True,
                        num_blocks=30)
    _, infos1 = audit_serve_engine(eng1, donate=True)
    assert all(not i["shardings"] for i in infos1)


def test_tp_param_shardings_cover_fused_blocks():
    """serve_param_shardings names a placement for every leaf the fused
    block dict actually holds — a renamed weight would KeyError at
    engine construction, not silently replicate. Since the quantized
    round the table also covers the int8 dequant scales
    (_quantize_decode_blocks), i.e. exactly the QUANTIZED dict's key
    set — both weight layouts look their placements up in one table."""
    from cxxnet_tpu.models.gpt import (_fuse_qkv_blocks,
                                       _quantize_decode_blocks)
    blocks = jax.eval_shape(_fuse_qkv_blocks, PARAMS["blocks"])
    qblocks = jax.eval_shape(_quantize_decode_blocks, blocks)
    bsh, osh = serve_param_shardings(_mesh())
    assert set(blocks) <= set(bsh)
    assert set(bsh) == set(qblocks)
    assert set(osh) == {"emb", "pos", "lnf_g", "lnf_b", "head"}


# ------------------------------------------------- guard + fused + obs
def test_tp_guard_signatures_carry_mesh_and_stay_single():
    rs = np.random.RandomState(4)
    jobs = [(_prompt(rs, n), 4, {}) for n in (3, 9, 14, 6)]
    with InferenceServer(CFG, PARAMS, slots=2, queue=8, prefill_chunk=4,
                         recompile_limit=4, tp=2) as srv:
        _serve_all(srv, jobs)
        eng = srv._engine
        assert len(eng.prefill_signatures) == 1
        assert len(eng.tick_signatures) == 1
        for sig in eng.prefill_signatures + eng.tick_signatures:
            assert "/mesh=" in str(sig), sig
    # the single-device engine's signatures stay suffix-free: tp=1 and
    # tp>1 programs can never collapse onto one counted signature
    with InferenceServer(CFG, PARAMS, slots=2, queue=8, prefill_chunk=4,
                         recompile_limit=4) as srv:
        _serve_all(srv, jobs[:1])
        assert all("/mesh=" not in str(s)
                   for s in srv._engine.prefill_signatures)


# Fused-under-TP identity runs a FOUR-head config: each of the two
# shards then holds 2 whole heads, and the per-shard kernel is bitwise
# the head slice of the single-device kernel. (XLA:CPU lowers a
# batch-1 head contraction through a different codepath whose
# low-order f32 bits can differ, so a one-head shard is numerically
# fine but not bitwise-pinned — engine module docstring.)
CFG4 = GPTConfig(vocab_size=32, seq_len=32, n_layer=2, n_head=4,
                 feat=32, n_microbatch=1)
PARAMS4 = gpt_init(jax.random.PRNGKey(7), CFG4)


def test_tp_fused_attn_resolves_on(monkeypatch):
    """Under TP the fused Pallas kernel now resolves ON (the shard_map
    wrap runs it per head shard; the support gate sees the LOCAL head
    count) — the PR 11 gather pin is gone — while CXN_FUSED_ATTN=0
    still arms the gather fallback."""
    from cxxnet_tpu.ops import pallas_kernels as pk
    monkeypatch.setattr(pk, "_INTERPRET", True)
    eng = DecodeEngine(CFG4, PARAMS4, 2, prefill_chunk=4, abstract=True,
                       num_blocks=30, mesh=_mesh(), fused_attn=True)
    assert eng.tp == 2
    assert eng.fused_attn is True
    assert eng.fused_formulation == "resident"
    monkeypatch.setenv("CXN_FUSED_ATTN", "0")
    eng = DecodeEngine(CFG4, PARAMS4, 2, prefill_chunk=4, abstract=True,
                       num_blocks=30, mesh=_mesh(), fused_attn=True)
    assert eng.fused_attn is False and eng.fused_formulation == ""


def _ref4(prompt, max_new, temperature=0.0, seed=0, **kw):
    rng = jax.random.PRNGKey(seed) if temperature > 0 else None
    return np.asarray(gpt_decode(PARAMS4, prompt[None], max_new, CFG4,
                                 temperature=temperature, rng=rng,
                                 **kw))[0]


def test_tp_fused_attn_identity(monkeypatch):
    """TP=2 FUSED decode (interpret mode: the kernel really runs,
    sharded per head) serves token streams bit-identical to solo
    gpt_decode — mixed lengths, shared-prefix hits, recycled slots, a
    sampled row (per-request ``spec_mode="off"`` drives the plain TICK
    program), and an ngram-speculative request through the fused TP
    VERIFY program, all on ONE server. (tp=1 fused == the same oracle
    is test_serve_fused.py's pin, so tp=2 == tp=1 follows.)"""
    from cxxnet_tpu.ops import pallas_kernels as pk
    monkeypatch.setattr(pk, "_INTERPRET", True)
    rs = np.random.RandomState(6)
    shared = rs.randint(0, CFG4.vocab_size, (9,)).astype(np.int32)
    off = dict(spec_mode="off")
    jobs = [(rs.randint(0, CFG4.vocab_size, (n,)).astype(np.int32), 5,
             dict(off)) for n in (11,)]
    jobs.append((np.concatenate(
        [shared, rs.randint(0, CFG4.vocab_size, (4,)).astype(np.int32)]),
        5, dict(off)))
    jobs.append((rs.randint(0, CFG4.vocab_size, (8,)).astype(np.int32),
                 5, dict(temperature=0.9, top_k=8, seed=3, **off)))
    base = rs.randint(0, CFG4.vocab_size, (5,)).astype(np.int32)
    jobs.append((np.concatenate([base, base, base[:2]]), 8, {}))
    refs = [_ref4(p, m, **{k: v for k, v in ov.items()
                           if k != "spec_mode"}) for p, m, ov in jobs]
    with InferenceServer(CFG4, PARAMS4, slots=2, queue=16,
                         prefill_chunk=4, spec_mode="ngram", spec_len=3,
                         tp=2, fused_attn=True) as srv:
        assert srv._engine.fused_attn is True
        got = _serve_all(srv, jobs)
        m = srv.metrics()
    for g, r in zip(got, refs):
        assert np.array_equal(g, r), (g, r)
    assert m["spec_forwards"] >= 1


def test_tp_fused_swap_identity(monkeypatch):
    """Rows coming back from host swap keep decoding exactly over the
    sharded fused kernel."""
    from cxxnet_tpu.ops import pallas_kernels as pk
    monkeypatch.setattr(pk, "_INTERPRET", True)
    rs = np.random.RandomState(7)
    swap_jobs = [(rs.randint(0, CFG4.vocab_size, (12,)).astype(np.int32),
                  10, {}) for _ in range(3)]
    swap_refs = [_ref4(p, m) for p, m, _ in swap_jobs]
    with InferenceServer(CFG4, PARAMS4, slots=3, queue=8,
                         prefill_chunk=4, num_blocks=13, tp=2,
                         degrade=False, fused_attn=True) as srv:
        assert srv._engine.fused_attn is True
        got = _serve_all(srv, swap_jobs)
        m = srv.metrics()["paged"]
    for g, r in zip(got, swap_refs):
        assert np.array_equal(g, r)
    assert m["swaps_out"] > 0 and m["swaps_in"] > 0


def test_tp_metrics_and_kv_sharding_live():
    with InferenceServer(CFG, PARAMS, slots=2, queue=4, prefill_chunk=4,
                         tp=2) as srv:
        m = srv.metrics()
        assert m["tp"] == 2
        assert "cxn_serve_tp 2" in srv.metrics_text()
        # the live pool really is head-sharded over the model axis
        spec = srv._engine.cache_k.sharding.spec
        assert tuple(spec) == (None, None, "model", None, None)
        # per-shard bytes are half the logical pool
        shard = next(iter(srv._engine.cache_k.addressable_shards))
        assert shard.data.size == srv._engine.cache_k.size // 2
