"""Tensor-parallel serving (gather-form TP, serve/engine.py module
docstring; doc/serving.md "Sharded & replicated serving").

The acceptance matrix on the forced multi-device CPU mesh
(tests/conftest.py): TP-sharded decode is BIT-IDENTICAL to the
single-device engine and to solo ``gpt_decode`` — greedy AND sampled,
since the gather form never splits a contraction — across chunked
prefill, prefix hits, recycled slots, speculative decoding, and paged
preemption/swap; the step audit sees the head-axis KV pool shardings
and zero all-reduces with donation aliasing intact; RecompileGuard
signatures carry the mesh shape; and the fused paged-attention kernel
pins the gather fallback under TP (the support gate evaluates the
LOCAL head count), with ``CXN_FUSED_ATTN=0`` still a no-op.
"""

import jax
import numpy as np
import pytest

from cxxnet_tpu.models.gpt import GPTConfig, gpt_decode, gpt_init
from cxxnet_tpu.parallel.mesh import make_mesh
from cxxnet_tpu.serve import DecodeEngine, InferenceServer
from cxxnet_tpu.serve.engine import (serve_kv_sharding,
                                     serve_param_shardings,
                                     serve_tp_size)

CFG = GPTConfig(vocab_size=32, seq_len=48, n_layer=2, n_head=2, feat=16,
                n_microbatch=1)
PARAMS = gpt_init(jax.random.PRNGKey(5), CFG)


def _mesh(tp=2):
    return make_mesh(devices=jax.devices()[:tp], model_parallel=tp)


def _prompt(rs, n):
    return rs.randint(0, CFG.vocab_size, (n,)).astype(np.int32)


def _ref(prompt, max_new, temperature=0.0, seed=0, **kw):
    rng = jax.random.PRNGKey(seed) if temperature > 0 else None
    return np.asarray(gpt_decode(PARAMS, prompt[None], max_new, CFG,
                                 temperature=temperature, rng=rng,
                                 **kw))[0]


def _serve_all(srv, jobs):
    """jobs: [(prompt, max_tokens, overrides)] -> token arrays, order
    preserved; every request must finish ok."""
    hs = [srv.submit(p, max_tokens=m, **ov) for p, m, ov in jobs]
    out = []
    for h in hs:
        r = srv.result(h, timeout=300)
        assert r.status == "ok", (r.status, r.error)
        out.append(r.tokens)
    return out


# ------------------------------------------------------------ validation
def test_tp_requires_divisible_heads_and_chunking():
    cfg3 = GPTConfig(vocab_size=32, seq_len=32, n_layer=1, n_head=3,
                     feat=18, n_microbatch=1)
    with pytest.raises(ValueError, match="divisible by the model-axis"):
        DecodeEngine(cfg3, gpt_init(jax.random.PRNGKey(0), cfg3), 2,
                     prefill_chunk=4, mesh=_mesh())
    with pytest.raises(ValueError, match="chunked prefill"):
        DecodeEngine(CFG, PARAMS, 2, prefill_chunk=0, mesh=_mesh())
    # a mesh without a >1 model axis is plain single-device serving
    eng = DecodeEngine(CFG, PARAMS, 2, prefill_chunk=4, abstract=True,
                       mesh=make_mesh(devices=jax.devices()[:1]))
    assert eng.tp == 1 and eng.mesh is None
    assert serve_tp_size(None) == 1


def test_server_tp_needs_enough_devices():
    with pytest.raises(ValueError, match="devices"):
        InferenceServer(CFG, PARAMS, slots=2, tp=99)


# ------------------------------------------------------- token identity
def test_tp_paged_bit_identical_mixed_traffic():
    """TP=2 paged serving: greedy AND sampled streams equal solo
    gpt_decode and the tp=1 engine across mixed lengths, shared-prefix
    hits, and recycled slots (more requests than slots)."""
    rs = np.random.RandomState(0)
    shared = _prompt(rs, 9)
    jobs = []
    for i, n in enumerate((6, 11, 3, 17, 7, 5)):
        jobs.append((_prompt(rs, n), 6, {}))
    jobs.append((np.concatenate([shared, _prompt(rs, 4)]), 5, {}))
    jobs.append((np.concatenate([shared, _prompt(rs, 2)]), 5, {}))
    # sampled rows: the gather form keeps logits bit-identical, so even
    # sampled tokens match the offline path exactly
    jobs.append((_prompt(rs, 8), 6,
                 dict(temperature=0.9, top_k=8, seed=3)))
    refs = [_ref(p, m, **ov) for p, m, ov in jobs]
    for tp in (1, 2):
        with InferenceServer(CFG, PARAMS, slots=2, queue=16,
                             prefill_chunk=4, tp=tp) as srv:
            assert srv.tp == tp
            got = _serve_all(srv, jobs)
        for g, r in zip(got, refs):
            assert np.array_equal(g, r), (tp, g, r)


def test_tp_dense_bit_identical():
    rs = np.random.RandomState(1)
    jobs = [(_prompt(rs, n), 6, {}) for n in (6, 11, 3)]
    refs = [_ref(p, m) for p, m, _ in jobs]
    with InferenceServer(CFG, PARAMS, slots=2, queue=8, prefill_chunk=4,
                         paged=False, tp=2) as srv:
        got = _serve_all(srv, jobs)
    for g, r in zip(got, refs):
        assert np.array_equal(g, r)


def test_tp_speculative_greedy_identical():
    rs = np.random.RandomState(2)
    # repetitive suffixes so the n-gram drafter actually proposes
    base = _prompt(rs, 5)
    jobs = [(np.concatenate([base, base, base[:2]]), 8, {}),
            (_prompt(rs, 7), 8, {})]
    refs = [_ref(p, m) for p, m, _ in jobs]
    with InferenceServer(CFG, PARAMS, slots=2, queue=8, prefill_chunk=4,
                         spec_mode="ngram", spec_len=3, tp=2) as srv:
        got = _serve_all(srv, jobs)
    for g, r in zip(got, refs):
        assert np.array_equal(g, r)


def test_tp_preempt_swap_resume_identity():
    """A pool small enough to force preemption + host swap under TP:
    the swap gather/scatter programs run over the head-sharded pool and
    the resumed rows stay bit-exact."""
    rs = np.random.RandomState(3)
    jobs = [(_prompt(rs, 12), 10, {}) for _ in range(4)]
    refs = [_ref(p, m) for p, m, _ in jobs]
    with InferenceServer(CFG, PARAMS, slots=4, queue=8, prefill_chunk=4,
                         num_blocks=14, tp=2, degrade=False) as srv:
        got = _serve_all(srv, jobs)
        m = srv.metrics()["paged"]
    for g, r in zip(got, refs):
        assert np.array_equal(g, r)
    # the tiny pool really exercised preemption + swap (14 blocks hold
    # ~2 of the 4 rows; measured 2 swap round trips at this geometry)
    assert m["swaps_out"] > 0 and m["swaps_in"] > 0


# ----------------------------------------------------------- step audit
def test_tp_audit_shardings_collectives_donation():
    """The compiled-step audit over the TP engine: abstract inputs
    carry the REAL mesh shardings (the head-axis KV pool spec shows up
    in the step info), donation aliasing survives partitioning, the
    collective count fits a pinned budget, and — the bit-identity
    invariant made structural — there are ZERO all-reduces: the gather
    form moves data, it never re-associates a contraction."""
    from cxxnet_tpu.analysis.step_audit import (audit_serve_engine,
                                                format_step_info)
    mesh = _mesh()
    eng = DecodeEngine(CFG, PARAMS, 2, prefill_chunk=4, abstract=True,
                       num_blocks=30, spec_len=2, mesh=mesh)
    report, infos = audit_serve_engine(eng, donate=True,
                                       collective_budget=8 * CFG.n_layer)
    assert report.ok(), report.format()
    assert {i["label"] for i in infos} == {
        "serve_prefill_chunk", "serve_verify_chunk", "serve_tick"}
    kv_spec = str(serve_kv_sharding(mesh).spec)
    for info in infos:
        assert kv_spec in info["shardings"], info
        assert info["collectives"]["all-reduce"] == 0, info
        assert info["collectives"]["all-gather"] > 0, info
        assert info["aliased"] == info["donated"] == 2, info
        assert "sharded[" in format_step_info(info)
    # an unsharded engine's audit reports no shardings (no regression
    # in the single-device step table)
    eng1 = DecodeEngine(CFG, PARAMS, 2, prefill_chunk=4, abstract=True,
                        num_blocks=30)
    _, infos1 = audit_serve_engine(eng1, donate=True)
    assert all(not i["shardings"] for i in infos1)


def test_tp_param_shardings_cover_fused_blocks():
    """serve_param_shardings names a placement for every leaf the fused
    block dict actually holds — a renamed weight would KeyError at
    engine construction, not silently replicate. Since the quantized
    round the table also covers the int8 dequant scales
    (_quantize_decode_blocks), i.e. exactly the QUANTIZED dict's key
    set — both weight layouts look their placements up in one table."""
    from cxxnet_tpu.models.gpt import (_fuse_qkv_blocks,
                                       _quantize_decode_blocks)
    blocks = jax.eval_shape(_fuse_qkv_blocks, PARAMS["blocks"])
    qblocks = jax.eval_shape(_quantize_decode_blocks, blocks)
    bsh, osh = serve_param_shardings(_mesh())
    assert set(blocks) <= set(bsh)
    assert set(bsh) == set(qblocks)
    assert set(osh) == {"emb", "pos", "lnf_g", "lnf_b", "head"}


# ------------------------------------------------- guard + fused + obs
def test_tp_guard_signatures_carry_mesh_and_stay_single():
    rs = np.random.RandomState(4)
    jobs = [(_prompt(rs, n), 4, {}) for n in (3, 9, 14, 6)]
    with InferenceServer(CFG, PARAMS, slots=2, queue=8, prefill_chunk=4,
                         recompile_limit=4, tp=2) as srv:
        _serve_all(srv, jobs)
        eng = srv._engine
        assert len(eng.prefill_signatures) == 1
        assert len(eng.tick_signatures) == 1
        for sig in eng.prefill_signatures + eng.tick_signatures:
            assert "/mesh=" in str(sig), sig
    # the single-device engine's signatures stay suffix-free: tp=1 and
    # tp>1 programs can never collapse onto one counted signature
    with InferenceServer(CFG, PARAMS, slots=2, queue=8, prefill_chunk=4,
                         recompile_limit=4) as srv:
        _serve_all(srv, jobs[:1])
        assert all("/mesh=" not in str(s)
                   for s in srv._engine.prefill_signatures)


def test_tp_fused_attn_pins_gather_fallback(monkeypatch):
    """Under TP the fused Pallas kernel resolves OFF (a Mosaic custom
    call GSPMD cannot partition) — the support gate sees the LOCAL head
    count, the engine pins the gather fallback, and CXN_FUSED_ATTN=0
    remains a no-op: streams are identical with the flag on, off, or
    env-killed."""
    from cxxnet_tpu.ops import pallas_kernels as pk
    # even with interpret mode waiving geometry limits (the gate would
    # say yes for the local heads), tp > 1 keeps the gather form
    monkeypatch.setattr(pk, "_INTERPRET", True)
    eng = DecodeEngine(CFG, PARAMS, 2, prefill_chunk=4, abstract=True,
                       num_blocks=30, mesh=_mesh(), fused_attn=True)
    assert eng.fused_attn is False
    monkeypatch.setattr(pk, "_INTERPRET", False)
    rs = np.random.RandomState(6)
    jobs = [(_prompt(rs, 7), 5, {})]
    refs = [_ref(p, m) for p, m, _ in jobs]
    for env in (None, "0"):
        if env is None:
            monkeypatch.delenv("CXN_FUSED_ATTN", raising=False)
        else:
            monkeypatch.setenv("CXN_FUSED_ATTN", env)
        with InferenceServer(CFG, PARAMS, slots=2, queue=4,
                             prefill_chunk=4, tp=2,
                             fused_attn=True) as srv:
            assert srv._engine.fused_attn is False
            got = _serve_all(srv, jobs)
        assert np.array_equal(got[0], refs[0])


def test_tp_metrics_and_kv_sharding_live():
    with InferenceServer(CFG, PARAMS, slots=2, queue=4, prefill_chunk=4,
                         tp=2) as srv:
        m = srv.metrics()
        assert m["tp"] == 2
        assert "cxn_serve_tp 2" in srv.metrics_text()
        # the live pool really is head-sharded over the model axis
        spec = srv._engine.cache_k.sharding.spec
        assert tuple(spec) == (None, None, "model", None, None)
        # per-shard bytes are half the logical pool
        shard = next(iter(srv._engine.cache_k.addressable_shards))
        assert shard.data.size == srv._engine.cache_k.size // 2
