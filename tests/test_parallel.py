"""Parallelism tests on the virtual 8-device CPU mesh.

Covers the reference's distribution capabilities re-expressed as SPMD
(SURVEY §2.7, §5.8): data parallelism, tensor parallelism (fullc_gather
descendant), ZeRO optimizer-state sharding (update_on_server descendant),
and the replica-consistency check (test_on_server, async_updater-inl.hpp:
144-154 — here: sharded runs must match the single-device run bitwise-ish).
"""

import jax
import numpy as np
import pytest

from cxxnet_tpu import Net
from cxxnet_tpu.io.data import DataBatch
from cxxnet_tpu.utils.config import tokenize

CFG = """
netconfig=start
layer[0->1] = conv:cv1
  kernel_size = 3
  pad = 1
  nchannel = 8
layer[1->2] = relu
layer[2->3] = max_pooling
  kernel_size = 2
  stride = 2
layer[3->4] = flatten
layer[4->5] = fullc:fc1
  nhidden = 64
layer[5->6] = relu
layer[6->7] = fullc:fc2
  nhidden = 10
layer[7->7] = softmax
netconfig=end
input_shape = 2,8,8
batch_size = 16
eta = 0.1
momentum = 0.9
seed = 3
metric = error
"""


def _make_batch(seed=0, n=16):
    rs = np.random.RandomState(seed)
    x = rs.rand(n, 2, 8, 8).astype(np.float32)
    y = rs.randint(0, 10, (n, 1)).astype(np.float32)
    return DataBatch(x, y)


def _train(extra_cfg, steps=3):
    net = Net(tokenize(CFG))
    for k, v in extra_cfg:
        net.set_param(k, v)
    net.init_model()
    for i in range(steps):
        net.update(_make_batch(seed=i))
    return net


def _params_np(net):
    return jax.tree.map(np.asarray, net.params)


def assert_params_close(a, b, tol=1e-5):
    flat_a, _ = jax.tree.flatten(a)
    flat_b, _ = jax.tree.flatten(b)
    assert len(flat_a) == len(flat_b)
    for ta, tb in zip(flat_a, flat_b):
        np.testing.assert_allclose(ta, tb, rtol=tol, atol=tol)


@pytest.fixture(scope="module")
def reference_run():
    return _params_np(_train([("dev", "cpu:0")]))


def test_data_parallel_matches_single_device(reference_run):
    net = _train([("dev", "cpu:0-7")])
    assert net.mesh.shape["data"] == 8
    assert_params_close(_params_np(net), reference_run)


def test_tensor_parallel_matches_single_device(reference_run):
    net = _train([("dev", "cpu:0-7"), ("model_parallel", "4")])
    assert net.mesh.shape["model"] == 4
    # fc weights actually sharded over the model axis
    sh = net.params["fc1"]["wmat"].sharding
    assert sh.spec[0] == "model"
    assert_params_close(_params_np(net), reference_run)


def test_zero_optimizer_sharding_matches_single_device(reference_run):
    net = _train([("dev", "cpu:0-7"), ("shard_optimizer", "1")])
    st = net.opt_state["fc1"]["wmat"]
    leaf = jax.tree.leaves(st)[0]
    assert "data" in tuple(leaf.sharding.spec)  # sharded over DP axis
    assert_params_close(_params_np(net), reference_run)


def test_tp_plus_zero_and_update_period(reference_run):
    # composed: dp x tp mesh + ZeRO + gradient accumulation still trains
    net = Net(tokenize(CFG))
    for k, v in [("dev", "cpu:0-7"), ("model_parallel", "2"),
                 ("shard_optimizer", "1"), ("update_period", "2")]:
        net.set_param(k, v)
    net.init_model()
    before = _params_np(net)
    net.update(_make_batch(seed=0))   # accumulate only
    assert_params_close(_params_np(net), before)
    net.update(_make_batch(seed=1))   # apply
    after = _params_np(net)
    diff = sum(float(np.abs(a - b).sum())
               for a, b in zip(jax.tree.leaves(after), jax.tree.leaves(before)))
    assert diff > 0


def test_replica_consistency_after_training():
    """test_on_server analogue: every device's view of a replicated weight
    must agree after sharded training."""
    net = _train([("dev", "cpu:0-7"), ("model_parallel", "2")])
    for arr in jax.tree.leaves(net.params):
        full = np.asarray(arr)
        for s in arr.addressable_shards:
            np.testing.assert_array_equal(np.asarray(s.data), full[s.index])


@pytest.mark.parametrize("level,update_period", [
    ("2", "1"), ("3", "1"),
    ("2", "2"),   # sharded gsum accumulation path (ZeRO-2 + update_period)
    ("3", "2"),
])
def test_zero23_matches_single_device(reference_run, level, update_period):
    """ZeRO-2 (gradients reduce-scattered) and ZeRO-3 (params
    data-sharded, FSDP-style) must train to the same weights as the
    single-device run — including with gradient accumulation, whose
    gsum buffer lives sharded under level >= 2 (accumulation changes the
    applied updates, so those cases get their own single-device
    reference)."""
    extra = [("update_period", update_period)] if update_period != "1" else []
    net = _train([("dev", "cpu:0-7"), ("shard_optimizer", level)] + extra)
    if level == "3":
        # params really are sharded over the data axis
        w = net.params["fc1"]["wmat"]
        assert "data" in tuple(w.sharding.spec), w.sharding
    ref = reference_run if update_period == "1" else _reference_up2()
    assert_params_close(_params_np(net), ref)


_UP2_REF = {}


def _reference_up2():
    """Single-device update_period=2 reference, computed once."""
    if "ref" not in _UP2_REF:
        _UP2_REF["ref"] = _params_np(
            _train([("dev", "cpu:0"), ("update_period", "2")]))
    return _UP2_REF["ref"]
