"""netconfig DSL / graph IR tests (reference grammar: nnet_config.h:207-360)."""

import pytest

from cxxnet_tpu.graph import NetGraph
from cxxnet_tpu.utils.config import ConfigError, tokenize


def build(text):
    return NetGraph().configure(tokenize(text))


MLP = """
netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 100
layer[+1:sg1] = sigmoid:se1
layer[sg1->fc2] = fullc:fc2
  nhidden = 10
layer[+0] = softmax
netconfig=end
input_shape = 1,1,784
"""


def test_mlp_structure():
    g = build(MLP)
    assert g.input_shape == (1, 1, 784)
    assert [l.type for l in g.layers] == ["fullc", "sigmoid", "fullc", "softmax"]
    # node 0 = in; fc1 -> node "fc1"; sigmoid -> "sg1"; fullc -> "fc2";
    # softmax self-loop on fc2
    assert g.layers[0].inputs == [0]
    assert g.node_names[g.layers[0].outputs[0]] == "fc1"
    assert g.layers[2].inputs == [g.node_map["sg1"]]
    assert g.layers[3].inputs == g.layers[3].outputs
    assert g.layer_name_map == {"fc1": 0, "se1": 1, "fc2": 2}


def test_layer_scoped_config():
    g = build(MLP)
    assert ("nhidden", "100") in g.layers[0].cfg
    assert ("nhidden", "10") in g.layers[2].cfg
    assert all(k != "nhidden" for k, _ in g.defcfg)


def test_numeric_nodes_and_self_loop():
    g = build("""
netconfig=start
layer[0->1] = conv:cv1
  kernel_size = 3
  nchannel = 8
layer[1->1] = relu
layer[1->2] = flatten
netconfig=end
input_shape = 3,8,8
""")
    assert g.layers[0].inputs == [0]
    assert g.layers[1].inputs == g.layers[1].outputs
    assert g.num_nodes == 3


def test_split_concat_multi_node():
    g = build("""
netconfig=start
layer[0->1,2] = split
layer[1->3] = fullc:a
  nhidden = 4
layer[2->4] = fullc:b
  nhidden = 4
layer[3,4->5] = concat
netconfig=end
input_shape = 1,1,8
""")
    assert g.layers[0].outputs == [1, 2]
    assert g.layers[3].inputs == [3, 4]


def test_share_layer():
    g = build("""
netconfig=start
layer[+1:h1] = fullc:enc
  nhidden = 8
layer[+1:h2] = sigmoid
layer[+1:h3] = share[enc]
netconfig=end
input_shape = 1,1,8
""")
    assert g.layers[2].type == "share"
    assert g.layers[2].primary == 0


def test_share_param_rejected():
    with pytest.raises(ConfigError):
        build("""
netconfig=start
layer[+1:h1] = fullc:enc
  nhidden = 8
layer[+1:h2] = share[enc]
  nhidden = 4
netconfig=end
""")


def test_undefined_input_node():
    with pytest.raises(ConfigError):
        build("netconfig=start\nlayer[nope->out] = relu\nnetconfig=end")


def test_unknown_layer_type():
    with pytest.raises(ConfigError):
        build("netconfig=start\nlayer[+1] = warp9\nnetconfig=end")


def test_label_vec_registry():
    g = build("label_vec[0,1) = label\nlabel_vec[1,4) = extra\n" + MLP)
    assert g.label_field("extra") == (1, 4)
    assert g.label_field("label") == (0, 1)


def test_structure_roundtrip():
    g = build(MLP)
    g2 = NetGraph.from_structure_state(g.structure_state())
    assert g2.node_names == g.node_names
    assert [l.type for l in g2.layers] == [l.type for l in g.layers]
    assert g2.layer_name_map == g.layer_name_map


def test_reconfigure_validates_structure():
    g = build(MLP)
    g.configure(tokenize(MLP))     # same structure ok
    with pytest.raises(ConfigError):
        g.configure(tokenize(MLP.replace("sigmoid", "tanh")))


def test_pairtest_decl():
    g = build("""
netconfig=start
layer[+1:c1] = pairtest-fullc-fullc:p1
  nhidden = 4
netconfig=end
input_shape = 1,1,8
""")
    assert g.layers[0].type == "pairtest"
    assert g.layers[0].pairtest == ("fullc", "fullc")


def test_share_forward_reference_rejected():
    """share[tag] naming a LATER layer must fail with an explicit
    forward-reference error, not a downstream lookup error."""
    cfg = tokenize("""
netconfig=start
layer[+1:a] = fullc:a
  nhidden = 4
layer[+1] = share[zz]
layer[+1:zz] = fullc:zz
  nhidden = 4
netconfig=end
input_shape = 1,1,8
""")
    with pytest.raises(ConfigError, match="forward reference"):
        NetGraph().configure(cfg)


def test_share_forward_reference_rejected_on_loaded_graph():
    """Re-configuring a loaded graph (fully populated name map) with a
    forward share also gets the explicit error."""
    base = """
netconfig=start
layer[+1:a] = fullc:a
  nhidden = 4
layer[+1:zz] = fullc:zz
  nhidden = 4
netconfig=end
input_shape = 1,1,8
"""
    g = NetGraph().configure(tokenize(base))
    g2 = NetGraph.from_structure_state(g.structure_state())
    bad = base.replace("layer[+1:a] = fullc:a", "layer[+1:a] = share[zz]")
    with pytest.raises(ConfigError, match="forward reference"):
        g2.configure(tokenize(bad))


def test_configure_attributes_error_lines():
    triples = tokenize("""
netconfig=start
layer[+1:a] = fullc:a
layer[+1] = bogustype
netconfig=end
input_shape = 1,1,8
""", with_lines=True)
    with pytest.raises(ConfigError) as ei:
        NetGraph().configure([(n, v) for n, v, _ in triples],
                             lines=[ln for _, _, ln in triples])
    assert ei.value.line == 4
