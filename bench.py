"""Benchmark driver: AlexNet ImageNet-shape training throughput on one chip.

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

Measures the jitted train step with device-resident data — the steady state
of a prefetching input pipeline (the framework's data plane double-buffers
host->device transfers; in this harness the host link is a network tunnel to
the chip, which no framework's step time should be charged for). The barrier
is a device-to-host fetch of the final loss: on the tunneled backend,
``block_until_ready`` returns before execution drains, so only a host fetch
truly synchronizes; its one-time RTT is amortized over BENCH_STEPS.

The paired pipeline-fed mode (real imgbin chain + StepStats data-wait
accounting) lives in tools/pipeline_bench.py — on this rig its step time
measures the host->device tunnel, so the two modes are reported
separately (doc/performance.md "Input pipeline").

Baseline: the driver-assigned north star is cxxnet's 4xK40 ImageNet AlexNet
throughput (BASELINE.md). The reference publishes no number; contemporary
cxxnet-era measurements put AlexNet at roughly 200 images/sec on one K40, so
4xK40 with "nearly linear speedup" (README.md:15-17) is taken as ~800
images/sec. vs_baseline = measured_images_per_sec / 800.
"""

import json
import os
import sys
import time

import numpy as np

# 64 MB scoped VMEM for fusions (default 16 MB): measured +4% AlexNet
# throughput on one v5e chip, repeatably (17.8 -> 18.5-18.6k img/s) —
# the big LRN/pool fusions get more working set. Neutral on the GPT
# flagship, so set here (the conv benchmark entry) rather than globally.
os.environ.setdefault("LIBTPU_INIT_ARGS",
                      "--xla_tpu_scoped_vmem_limit_kib=65536")

BASELINE_IMAGES_PER_SEC = 800.0
# 1024 = the reference's ImageNet batch 256 (ImageNet.conf) scaled to the
# chip's throughput sweet spot (measured with the band-matmul LRN: ~16k
# img/s @512, ~17k @1024 repeatably — the MXU wants the larger GEMMs;
# 2048 fits with bf16 feeds but measured slightly slower, 17.8k vs 18.1k)
BATCH = 1024
WARMUP_STEPS = 3
BENCH_STEPS = 50


def main() -> int:
    import jax
    import jax.numpy as jnp
    from cxxnet_tpu import Net
    from cxxnet_tpu.models import alexnet_config
    from cxxnet_tpu.utils.config import tokenize

    n_dev = len(jax.devices())
    batch = BATCH
    if batch % n_dev:
        batch = (batch // n_dev + 1) * n_dev

    net = Net(tokenize(alexnet_config(batch_size=batch, dev="",
                                      precision="bfloat16")))
    net.init_model()

    rs = np.random.RandomState(0)
    x = rs.rand(batch, 3, 227, 227).astype(np.float32)
    y = rs.randint(0, 1000, (batch, 1)).astype(np.float32)

    class _B:
        data, label, extra_data = x, y, []

    # steady state of a `data_dtype = bfloat16` + `threadbuffer` pipeline:
    # batches arrive bf16 (converted in the prefetch producer thread), so
    # the step's input cast no-ops — feed the same thing here
    import ml_dtypes
    _B.data = _B.data.astype(ml_dtypes.bfloat16)
    data, extras, label = net._device_batch(_B())
    rng = jax.random.PRNGKey(0)
    epoch = jnp.asarray(0, jnp.int32)

    p, o, s = net.params, net.opt_state, net.states
    for _ in range(WARMUP_STEPS):
        p, o, s, loss, _ = net._jit_update(p, o, s, data, extras, label,
                                           None, rng, epoch)
    float(loss)              # true barrier: drain the dispatch queue

    t0 = time.perf_counter()
    for _ in range(BENCH_STEPS):
        p, o, s, loss, _ = net._jit_update(p, o, s, data, extras, label,
                                           None, rng, epoch)
    float(loss)              # single host fetch barriers the whole run
    dt = time.perf_counter() - t0

    images_per_sec = BENCH_STEPS * batch / dt
    print(json.dumps({
        "metric": "alexnet_train_images_per_sec",
        "value": round(images_per_sec, 2),
        "unit": "images/sec",
        "vs_baseline": round(images_per_sec / BASELINE_IMAGES_PER_SEC, 3),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
