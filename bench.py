"""Benchmark driver: the framework's full headline set on one chip.

Prints one JSON line per metric, in this order:
  1. alexnet_train_images_per_sec   (vs_baseline = cxxnet 4xK40 north star)
  2. resnet50_train_images_per_sec  (the round-4 roofline target)
  3. train_feed_overlap             (async device feed: 1 - feed_wait
                                     fraction, steady state, round 6)
  4. gpt_train_tokens_per_sec       (305M d128 flagship, batch 24)
  5. gpt_train_mfu_param_attn       (vs the r4 RECORDED 0.6256 — pinned
                                     like every other metric, round 7)
  5b. gpt_train_mfu_xla             (same step, numerator = XLA's own
                                     cost_analysis() flops and
                                     denominator = devprof hw_peaks —
                                     the observatory's one source of
                                     truth, round 12; the analytic
                                     line above keeps the historical
                                     trajectory)
  6. moe_dispatch_tokens_per_sec    (E=32 sort top-2 fwd+bwd, S=16384;
                                     best-of-3 cells, band recorded)
  7. gpt_decode_ms_per_token        (85M batch-1, cache 1024, fused
                                     whole-step kernel; r3 quoted 0.74;
                                     best-of-5 since round 7)
  7b. gpt_decode_spec_ms_per_token  (speculative draft-and-verify decode,
                                     n-gram drafter on a repetitive-
                                     suffix prompt; vs_baseline = the
                                     same prompt non-speculative,
                                     round 10)
  8. serve_tokens_per_sec           (continuous-batching serving cell:
                                     steady-state aggregate tokens/s of
                                     the slot scheduler under an open-
                                     loop arrival trace, round 7)
  9. serve_p95_ttft_ms              (same trace: p95 time-to-first-token
                                     including queue wait)
 10. serve_vs_sequential            (same trace served one-at-a-time
                                     through gpt_decode / served wall —
                                     >1 means continuous batching wins)
 11. serve_prefix_hit_tokens_per_sec (prefill-heavy shared-prefix trace:
                                     prompt tokens served straight from
                                     the prefix KV cache per second,
                                     round 9)
 12. serve_p95_ttft_ms_prefill_heavy (same trace, chunked prefill +
                                     prefix reuse; vs_baseline = the
                                     SAME trace through the legacy
                                     whole-prompt prefill — >1 means
                                     chunking + reuse cut p95 TTFT)
 12a. serve_tokens_per_mib          (paged KV cache: the PREFIX_CELL
                                     trace at 4x request concurrency,
                                     dense vs paged under the SAME KV
                                     MiB budget; vs_baseline = paged /
                                     dense tokens-per-MiB — >= 1.5 is
                                     the round-13 acceptance gate)
 12a'. serve_p95_ttft_ms_paged      (same paged run's p95 TTFT;
                                     vs_baseline = dense p95 / paged)
 12a''. serve_tokens_per_sec_fused  (fused paged-attention kernel: the
                                     serve_paged trace served by the
                                     paged engine with the fused Pallas
                                     tick/verify vs the XLA gather
                                     formulation; vs_baseline = fused /
                                     gather tokens/s — the arms are
                                     identical (ratio ~1.0) on backends
                                     where the kernel is unsupported
                                     and both resolve to gather, which
                                     is itself the off-switch no-op
                                     check; cxn_mfu{fn=serve_tick}
                                     rides along as an attribute,
                                     round 16)
 12a''l. serve_tokens_per_sec_longctx (long-prompt paged trace with the
                                     rows pushed past the resident
                                     VMEM gate: streaming-fused vs
                                     gather arms; ~1.0 where the
                                     kernel is unsupported and both
                                     arms resolve gather)
 12a''t. autotune_wall_ms           (the task=autotune sweep's wall
                                     cost: every serve_block_size
                                     divisor of the chunk built and
                                     its AOT tick timed; paid once
                                     per fleet — the executables and
                                     the winner persist via the AOT
                                     cache)
 12a''u. serve_tokens_per_sec_tuned (the same trace served at the
                                     default geometry vs
                                     serve_block_size=auto loading
                                     the persisted winner; ~1.0 when
                                     the default already won)
 12a3. serve_tokens_per_sec_tp2     (tensor-parallel serving: the
                                     REPL_CELL trace served by the tp=2
                                     gather-form TP engine — KV pool
                                     head-sharded over a 2-device mesh
                                     — vs the single-device engine;
                                     tokens bit-identical, so the
                                     ratio is pure partitioning
                                     overhead on a shared-core CPU rig
                                     and the memory-per-chip win on a
                                     real one, round 17)
 12a4. serve_tokens_per_sec_replicated (2 engine replicas behind the
                                     prefix/health router vs one
                                     engine; ~Nx on N-device rigs,
                                     pinned honest on shared cores,
                                     round 17)
 12a5. serve_goodput_replicated_kill (completed-request fraction with
                                     an engine chaos-killed mid-trace,
                                     restart budget 0: the router
                                     replays the dead replica's
                                     requests on the survivor;
                                     vs_baseline = router / single
                                     completed fraction — the
                                     availability headline, round 17)
 12a5f. serve_tokens_per_sec_fleet  (cross-process fleet: 1 prefill +
                                     2 decode worker processes behind
                                     the RPC router, KV records
                                     migrating over sockets;
                                     vs_baseline = fleet / in-process
                                     2-replica router — the wire tax
                                     on shared cores, round 18)
 12a5g. serve_goodput_fleet_kill    (completed-request fraction with a
                                     decode worker SIGKILLed
                                     mid-trace: the fleet router
                                     replays the dead worker's journal
                                     on the survivor; vs_baseline =
                                     fleet / single chaos-killed
                                     engine, round 18)
 12a6. serve_goodput_guaranteed_overload (multi-tenant SLO cell: a
                                     3x-overload Poisson trace with a
                                     G/S/B tenant mix — the guaranteed
                                     tenant's completion fraction must
                                     hold 1.0 while best-effort sheds
                                     with finite retry hints)
 12a7. serve_p95_ttft_ms_guaranteed_overload (same trace: guaranteed
                                     p95 TTFT; vs_baseline = the
                                     untenanted global-FIFO server's
                                     guaranteed p95 / tenanted — the
                                     latency-isolation win)
 12b. serve_spec_tokens_per_sec     (speculative serving: n-gram drafter
                                     on a repetitive-suffix trace;
                                     vs_baseline = the same trace served
                                     without speculation, round 10)
 12c. obs_overhead_pct              (serving throughput cost of leaving
                                     span tracing on, SERVE_CELL trace
                                     served with tracing on vs off; the
                                     obs cost budget is <= 2%, round 11;
                                     since round 12 both arms also run
                                     the devprof live sampler at its
                                     default cadence, so the gate
                                     covers the full shipped telemetry)
 13. lint_wall_ms                   (cxn-lint pass 1 on the largest
                                     example config — the CXN_LINT
                                     startup/CI cost, round 8)
 13b. lint_threads_wall_ms          (cxn-lint pass 3 — the CXN3xx
                                     concurrency lint over the whole
                                     package source, the new tier-1
                                     CI gate's cost, round 19)

Round 3's bench emitted only the AlexNet line, which had plateaued at the
chip's proven streaming ceiling — the driver-recorded BENCH_r*.json could no
longer see where the perf work actually happened (VERDICT r3 #2). Each
benchmark is isolated in try/except and device buffers are dropped between
benchmarks, so a failure or OOM in one cannot silence the others.

All measurements are device-resident steady state (the host link on this
rig is a network tunnel to the chip; no framework's step time should be
charged for it) with a single host fetch as the barrier: on the tunneled
backend ``block_until_ready`` returns before execution drains, so only a
host fetch truly synchronizes; its one-time RTT is amortized over the steps.

Baseline: the driver-assigned north star is cxxnet's 4xK40 ImageNet AlexNet
throughput (BASELINE.md). The reference publishes no number; contemporary
cxxnet-era measurements put AlexNet at roughly 200 images/sec on one K40, so
4xK40 with "nearly linear speedup" (README.md:15-17) is taken as ~800
images/sec. vs_baseline = measured_images_per_sec / 800.
"""

import gc
import json
import os
import sys
import time

import numpy as np

# 64 MB scoped VMEM for fusions (default 16 MB): measured +4% AlexNet
# throughput on one v5e chip, repeatably (17.8 -> 18.5-18.6k img/s) —
# the big LRN/pool fusions get more working set. Neutral on the GPT
# flagship and the rest of the zoo.
os.environ.setdefault("LIBTPU_INIT_ARGS",
                      "--xla_tpu_scoped_vmem_limit_kib=65536")

# forced virtual host devices for the sharded/replicated serving cells
# (round 17): affects only the HOST (CPU) platform — a no-op on real
# TPU rigs — and gives the CPU rig the multi-device mesh serve_tp
# needs (tests/conftest.py forces the same for the suite). Must happen
# before jax initializes, which is why it sits at module import.
if "xla_force_host_platform_device_count" \
        not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8").strip()

BASELINE_IMAGES_PER_SEC = 800.0
# hardware peaks (FLOP/s + HBM bytes/s) come from the devprof
# observatory's single source of truth (obs/devprof.py:hw_peaks —
# device-kind table with CXN_PEAK_* overrides); bench.py pinning its
# own 197e12 was the drift the observatory replaces. The recorded
# BASELINE/BENCH trajectory is unaffected: on the v5e rig hw_peaks
# returns the identical number, and on unknown kinds it FALLS BACK to
# the v5e figure rather than inventing a new denominator.

# Round-4 recorded values (BENCH_r04.json), pinned as baselines so a
# regression in ANY headline metric shows up as vs_baseline < 1 in the next
# driver run instead of needing an eyeball diff across BENCH_r*.json files
# (VERDICT r4 weak #5). Throughput metrics report value/baseline; the decode
# latency metric reports baseline/value — in every line >1.0 means better
# than round 4.
R4_RESNET50_IPS = 2309.06
R4_GPT_TOKENS_PER_SEC = 64619.5
R4_GPT_MFU = 0.6256             # the r4 RECORDED value (BENCH_r04.json),
#                                 pinned like every other metric — the
#                                 old 0.620 was the r3 QUOTED number, so
#                                 the MFU line was the one headline whose
#                                 vs_baseline diffed against a different
#                                 era than its siblings (VERDICT r5 #10)
R4_MOE_TOKENS_PER_SEC = 913375.5
R4_DECODE_MS_PER_TOKEN = 0.3934


def gpt_model_flops(n_params, batch, seq, feat, layers):
    """Strict model FLOPs per step: 6*N per token (fwd 2N + bwd 4N) plus
    causal attention 6*n^2*f per layer per sequence (QK^T + PV, causality
    halves, bwd is 2x fwd). Remat recompute is NOT credited. The single
    definition — tools/gpt_bench.py imports this so the headline MFU and
    the analysis tool's cannot drift."""
    return (6.0 * n_params * batch * seq
            + 6.0 * seq * seq * feat * layers * batch)


def round_up(batch, n_dev):
    """Round a benchmark batch up to a multiple of the device count so the
    data sharding always divides (no-op on one chip)."""
    return batch if batch % n_dev == 0 else (batch // n_dev + 1) * n_dev


def emit(metric, value, unit, vs_baseline=None, **extra):
    """One JSON line per metric. ``extra`` lands in the record verbatim —
    e.g. the MoE cell's best-of band, so a vs_baseline swing can be read
    against the cell's own run-to-run spread instead of eyeballed."""
    rec = {"metric": metric, "value": round(value, 4), "unit": unit,
           "vs_baseline": (round(vs_baseline, 3)
                           if vs_baseline is not None else None)}
    rec.update(extra)
    print(json.dumps(rec), flush=True)


def prepare_cnn(config_text, batch, f32_feed=False):
    """Build a Net + device-resident synthetic batch for step timing.

    Returns (net, step_args) where step_args feeds run_steps below. The
    single shared definition of the measurement protocol — tools/cnn_bench.py
    imports these so headline and analysis numbers cannot drift apart.
    """
    import jax
    import jax.numpy as jnp
    import ml_dtypes
    from cxxnet_tpu import Net
    from cxxnet_tpu.utils.config import tokenize

    net = Net(tokenize(config_text))
    net.init_model()
    shape = net.graph.input_shape
    rs = np.random.RandomState(0)
    # steady state of a `data_dtype = bfloat16` + `threadbuffer` pipeline:
    # batches arrive bf16 (converted in the prefetch producer thread)
    x = rs.rand(batch, *shape).astype(np.float32)
    if not f32_feed:
        x = x.astype(ml_dtypes.bfloat16)
    y = rs.randint(0, 1000, (batch, 1)).astype(np.float32)

    class _B:
        data, label, extra_data = x, y, []

    data, extras, label = net._device_batch(_B())
    rng = jax.random.PRNGKey(0)
    epoch = jnp.asarray(0, jnp.int32)
    return net, (data, extras, label, rng, epoch)


def prepare_lm(config_text, batch, seq, vocab):
    """LM twin of prepare_cnn: build a Net from a gpt_lm_config text +
    a device-resident synthetic token batch (ids as data AND label).
    Shares run_steps, so the LM measurement protocol cannot drift from
    the CNN one."""
    import jax
    import jax.numpy as jnp
    from cxxnet_tpu import Net
    from cxxnet_tpu.utils.config import tokenize

    net = Net(tokenize(config_text))
    net.init_model()
    rs = np.random.RandomState(0)
    ids = rs.randint(0, vocab, (batch, seq)).astype(np.float32)

    class _B:
        data, label, extra_data = ids.reshape(batch, 1, 1, seq), ids, []

    data, extras, label = net._device_batch(_B())
    rng = jax.random.PRNGKey(0)
    epoch = jnp.asarray(0, jnp.int32)
    return net, (data, extras, label, rng, epoch)


def run_steps(net, step_args, n):
    """Run n jitted train steps; returns elapsed seconds (host-fetch barrier:
    on tunneled backends block_until_ready returns before execution drains,
    so only a host fetch truly synchronizes)."""
    data, extras, label, rng, epoch = step_args
    p, o, s, ma = net.params, net.opt_state, net.states, net._train_accum
    t0 = time.perf_counter()
    for _ in range(n):
        p, o, s, ma, loss, _ = net._jit_update(p, o, s, ma, data, extras,
                                               label, None, rng, epoch)
    float(loss)
    net.params, net.opt_state, net.states = p, o, s
    net._train_accum = ma
    return time.perf_counter() - t0


def _cnn_step_time(config_text, batch, warmup, steps):
    """Measure the jitted train step of a netconfig model, device-resident."""
    net, step_args = prepare_cnn(config_text, batch)
    run_steps(net, step_args, warmup)       # compile + spin up
    return run_steps(net, step_args, steps) / steps


def bench_alexnet():
    import jax
    from cxxnet_tpu.models import alexnet_config
    # 1024 = the reference's ImageNet batch 256 scaled to the chip's
    # throughput sweet spot (measured: ~16.6k img/s @512, ~18.5k @1024;
    # 2048 fits with bf16 feeds but measured slightly slower)
    batch = round_up(1024, len(jax.devices()))
    dt = _cnn_step_time(alexnet_config(batch_size=batch, dev="",
                                       precision="bfloat16"),
                        batch, warmup=3, steps=50)
    ips = batch / dt
    emit("alexnet_train_images_per_sec", ips, "images/sec",
         ips / BASELINE_IMAGES_PER_SEC)


def bench_resnet50():
    import jax
    from cxxnet_tpu.models import resnet_config
    batch = round_up(256, len(jax.devices()))
    dt = _cnn_step_time(resnet_config(50, batch_size=batch, dev="",
                                      precision="bfloat16"),
                        batch, warmup=3, steps=20)
    ips = batch / dt
    emit("resnet50_train_images_per_sec", ips, "images/sec",
         ips / R4_RESNET50_IPS)


FEED_OVERLAP_CONF = """
netconfig=start
layer[+1] = conv:cv1
  kernel_size = 3
  pad = 1
  nchannel = 32
layer[+1] = relu
layer[+1] = max_pooling
  kernel_size = 2
  stride = 2
layer[+1] = flatten
layer[+1] = fullc:fc1
  nhidden = 10
layer[+0] = softmax
netconfig=end
input_shape = 3,32,32
batch_size = %d
precision = bfloat16
eval_train = 1
metric = error
eta = 0.01
"""


class _RepeatBatches:
    """Host iterator yielding the same DataBatch n times per epoch — the
    feed-overlap bench's stand-in for a real pipeline (the placement cost
    per batch is what matters, not decode)."""

    def __init__(self, batch, n):
        self.batch, self.n, self.i = batch, n, 0

    def before_first(self):
        self.i = 0

    def next(self):
        self.i += 1
        return self.i <= self.n

    def value(self):
        return self.batch


def bench_feed_overlap():
    """Steady-state feed overlap of the async training feed (round 6): a
    small image model is trained end to end through ``Net.update`` fed by
    a ``DevicePrefetcher`` (depth 2 — the CLI's `prefetch_to_device`
    default) with on-device train-metric accumulation, and the fraction
    of wall time the consumer loop spends blocked on the feed queue is
    measured with StepStats. Emitted value = 1 - feed_wait fraction:
    ~1.0 means batch k+1's host->device placement is fully hidden behind
    step k's compute. The image-model HEADLINE benches above stay
    device-resident (module docstring: this rig's host link is a network
    tunnel whose per-batch cost is a harness artifact, so a per-step
    host feed would measure the tunnel, not the framework) — this line
    is where the async feed's overlap is observable on any rig."""
    import jax
    from cxxnet_tpu import Net
    from cxxnet_tpu.io.data import DataBatch
    from cxxnet_tpu.io.device_prefetch import DevicePrefetcher
    from cxxnet_tpu.utils import profiler
    from cxxnet_tpu.utils.config import tokenize

    batch = round_up(256, len(jax.devices()))
    net = Net(tokenize(FEED_OVERLAP_CONF % batch))
    net.init_model()
    rs = np.random.RandomState(0)
    host = DataBatch(rs.rand(batch, 3, 32, 32).astype(np.float32),
                     rs.randint(0, 10, (batch, 1)).astype(np.float32))
    net.update(host)                      # compile + warm
    float(net.last_loss())
    steps = 24
    feed = DevicePrefetcher(net.place_batch, _RepeatBatches(host, steps),
                            depth=2)
    try:
        stats = profiler.StepStats(batch_size=batch)
        feed.before_first()
        while True:
            with stats.phase(profiler.FEED_WAIT):
                has = feed.next()
            if not has:
                break
            with stats.phase(profiler.STEP_DISPATCH):
                net.update(feed.value())
            stats.end_step()
        float(net.last_loss())            # drain barrier inside the wall
        overlap = 1.0 - stats.wait_fraction()
    finally:
        feed.close()
    emit("train_feed_overlap", overlap, "fraction")


def bench_gpt():
    """The 305M d128 flagship, trained through the UNIFIED config-DSL
    surface (round 5): gpt_lm_config -> Net -> one jitted step. Measured
    on one v5e chip the config path BEATS the round-4 functional
    (models/gpt.py) cell — 74.8k vs 64.2k tok/s (72.4% vs 62.2% MFU) —
    because the unrolled per-block execution avoids gpipe's trivial
    shard_map/scan on one chip and the QKV weight is STORED fused (one
    (F,3F) matmul with no per-step concat, where the scan path re-ran
    the concat each layer; doc/performance.md round 5). remat=0: the 305M
    @ 24x1024 fits HBM without remat; remat block/attn_saved measured
    60.9k/67.3k tok/s as the memory-pressure options."""
    import jax
    from cxxnet_tpu.models import gpt_lm_config

    batch, seq, vocab = round_up(24, len(jax.devices())), 1024, 256
    cfg = gpt_lm_config(seq_len=seq, vocab_size=vocab, feat=2048, nhead=16,
                        nblock=6, batch_size=batch, precision="bfloat16",
                        remat=0, attn_layout="auto", updater="adam",
                        eta=1e-4)
    cfg += "\neval_train = 0\n"       # metric outs dead-code-eliminated
    net, args = prepare_lm(cfg, batch, seq, vocab)
    from cxxnet_tpu.models.gpt import gpt_num_params
    n_params = gpt_num_params(net.params)
    run_steps(net, args, 3)
    steps = 15
    dt = run_steps(net, args, steps) / steps

    from cxxnet_tpu.obs import devprof
    peaks = devprof.hw_peaks()
    tokens = batch * seq
    flops = gpt_model_flops(n_params, batch, seq, 2048, 6)
    mfu = flops / dt / peaks.flops
    tps = tokens / dt
    emit("gpt_train_tokens_per_sec", tps, "tokens/sec",
         tps / R4_GPT_TOKENS_PER_SEC)
    # the analytic (6N + attention) MFU keeps its name and its r4
    # baseline so the recorded trajectory stays comparable...
    emit("gpt_train_mfu_param_attn", mfu, "fraction", mfu / R4_GPT_MFU)
    # ...and the cost-table MFU rides next to it: the numerator is
    # XLA's OWN flop count for the compiled update step (remat
    # recompute and fused epilogues included — everything the analytic
    # formula deliberately excludes), so the two lines bracket the true
    # utilization. doc/performance.md records both values once
    # (round 12) for the cutover. Guarded: a backend without
    # cost_analysis skips the line instead of mislabeling it.
    from cxxnet_tpu.analysis.step_audit import net_step_specs
    label, fn, spec_args, _, _ = net_step_specs(net)[0]   # net_update
    pc, _ = devprof.extract_program(fn, spec_args, label)
    if pc.available and pc.flops > 0:
        mfu_xla = pc.flops / dt / peaks.flops
        emit("gpt_train_mfu_xla", mfu_xla, "fraction",
             flops_per_step=pc.flops, analytic_mfu=round(mfu, 4),
             peak_source=peaks.source)
    else:
        print("bench_gpt: cost_analysis unavailable here; skipping the "
              "gpt_train_mfu_xla line (%s)" % pc.note, file=sys.stderr)


def moe_dispatch_cell(S, D, H, E, dispatch, top_k, steps=15):
    """fwd+bwd seconds/step of one switch_moe cell — the single measurement
    definition shared with tools/moe_bench.py."""
    import jax
    import jax.numpy as jnp
    from cxxnet_tpu.ops.moe import switch_moe

    rs = np.random.RandomState(0)
    wg = jnp.asarray(rs.randn(D, E).astype(np.float32) * 0.02)
    wu = jnp.asarray(rs.randn(E, D, H).astype(np.float32) * 0.02
                     ).astype(jnp.bfloat16)
    wd = jnp.asarray(rs.randn(E, H, D).astype(np.float32) * 0.02
                     ).astype(jnp.bfloat16)
    x = jnp.asarray(rs.randn(S, D).astype(np.float32)).astype(jnp.bfloat16)

    def loss(xx, g, u, dn):
        out, aux = switch_moe(xx, g, u, dn, 1.25, dispatch=dispatch,
                              top_k=top_k)
        return jnp.sum(out.astype(jnp.float32) ** 2) + aux

    f = jax.jit(jax.value_and_grad(loss, argnums=(0, 2, 3)))
    r = f(x, wg, wu, wd)
    float(r[0])              # host fetch: the true barrier
    t0 = time.perf_counter()
    for _ in range(steps):
        r = f(x, wg, wu, wd)
    float(r[0])
    return (time.perf_counter() - t0) / steps


def bench_moe():
    """Sort-based top-2 dispatch at E=32 (tools/moe_bench.py headline
    cell). Best-of-3 CELLS (each itself a 15-step mean) with the band
    recorded in the JSON line: the r4/r5 single-cell numbers swung a few
    percent run to run, which a lone value lets masquerade as a
    regression or a win (VERDICT r5 #9)."""
    S = 16384
    cells = [moe_dispatch_cell(S, 1024, 2048, 32, "sort", 2)
             for _ in range(3)]
    tps = S / min(cells)
    emit("moe_dispatch_tokens_per_sec", tps, "tokens/sec",
         tps / R4_MOE_TOKENS_PER_SEC,
         band=[round(S / max(cells), 1), round(tps, 1)])


# the headline decode cell's geometry — single source for decode_cell's
# defaults AND bench_decode's int8-path gate (a drifting copy of these
# constants is how a gate silently tests the wrong signature)
DECODE_CELL = dict(layers=12, heads=12, feat=768, seq=1024, prompt_len=16)


def decode_cell(layers=DECODE_CELL["layers"], heads=DECODE_CELL["heads"],
                feat=DECODE_CELL["feat"], seq=DECODE_CELL["seq"],
                prompt_len=DECODE_CELL["prompt_len"],
                batch=1, reps=3, int8=False):
    """Best-of-reps seconds/token for KV-cache decode — the single
    measurement definition shared with tools/decode_bench.py."""
    import jax
    from cxxnet_tpu.models.gpt import GPTConfig, gpt_decode, gpt_init

    cfg = GPTConfig(vocab_size=256, seq_len=seq, n_layer=layers,
                    n_head=heads, feat=feat, n_microbatch=1,
                    dtype="bfloat16")
    params = gpt_init(jax.random.PRNGKey(0), cfg)
    rs = np.random.RandomState(0)
    prompt = jax.numpy.asarray(
        rs.randint(0, 256, (batch, prompt_len)).astype(np.int32))
    max_new = seq - prompt_len
    np.asarray(gpt_decode(params, prompt, max_new, cfg,
                          int8_weights=int8))               # compile
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        np.asarray(gpt_decode(params, prompt, max_new, cfg,
                              int8_weights=int8))
        best = min(best, time.perf_counter() - t0)
    return best / max_new


# the speculative decode cell: the decode-cell geometry with a
# repetitive-suffix prompt — a steady-state window CUT FROM THE MODEL'S
# OWN greedy stream (random-init models don't continue an arbitrary
# tiled pattern, but they do keep producing self-similar text, which is
# exactly the traffic shape the n-gram/prompt-lookup drafter hits on any
# checkpoint). Single source so the spec and non-spec passes cannot
# drift onto different prompts.
SPEC_CELL = dict(prompt_len=64, warm_tokens=120, spec_len=8, max_new=256)


def bench_decode_spec():
    """Speculative offline decode (round 10, doc/serving.md): the
    decode-cell model with the n-gram drafter on a repetitive-suffix
    prompt, best-of-3 warm. vs_baseline = the SAME prompt through the
    plain (non-speculative) decode, measured in the same run — > 1.0
    means draft-and-verify beats one-forward-per-token; the line also
    records the observed accept_rate, since the win degrades to a small
    loss (per-verify overhead) when the drafter stops hitting."""
    import jax
    from cxxnet_tpu.models.gpt import GPTConfig, gpt_decode, gpt_init

    c, s = DECODE_CELL, SPEC_CELL
    cfg = GPTConfig(vocab_size=256, seq_len=c["seq"], n_layer=c["layers"],
                    n_head=c["heads"], feat=c["feat"], n_microbatch=1,
                    dtype="bfloat16")
    params = gpt_init(jax.random.PRNGKey(0), cfg)
    rs = np.random.RandomState(0)
    seed = jax.numpy.asarray(rs.randint(0, 256, (1, 8)).astype(np.int32))
    warm = np.asarray(gpt_decode(params, seed, s["warm_tokens"], cfg))[0]
    prompt = jax.numpy.asarray(
        warm[None, -s["prompt_len"]:].astype(np.int32))
    max_new = min(s["max_new"], c["seq"] - s["prompt_len"])
    spec = {"mode": "ngram", "spec_len": s["spec_len"], "stats": {}}

    def run(sp):
        np.asarray(gpt_decode(params, prompt, max_new, cfg,
                              speculative=sp))        # warm/compile
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            np.asarray(gpt_decode(params, prompt, max_new, cfg,
                                  speculative=sp))
            best = min(best, time.perf_counter() - t0)
        return best / max_new

    base_ms = run(None) * 1e3
    spec_ms = run(spec) * 1e3
    emit("gpt_decode_spec_ms_per_token", spec_ms, "ms/token",
         base_ms / spec_ms,
         accept_rate=round(spec["stats"]["accept_rate"], 3),
         spec_tokens_per_forward=round(
             spec["stats"]["spec_tokens_per_forward"], 2),
         plain_ms_per_token=round(base_ms, 4))


def bench_decode():
    """Batch-1 KV-cache decode on the 85M model (fused whole-step kernel
    auto-engages; tools/decode_bench.py is the A/B harness). The int8
    line is the opt-in weight-streaming quantization (round 5) — both
    compare against the round-4 bf16 baseline. Best-of-5 since round 7:
    the r5 lines were best-of-2, thin enough for dispatch jitter to move
    vs_baseline by itself (VERDICT r5 #9)."""
    ms = decode_cell(reps=5) * 1e3
    emit("gpt_decode_ms_per_token", ms, "ms/token",
         R4_DECODE_MS_PER_TOKEN / ms)
    # only emit the int8 line when the int8 fused path can actually
    # engage for this cell's signature — otherwise gpt_decode silently
    # falls back to bf16 and the number would be mislabeled
    from cxxnet_tpu.ops.pallas_kernels import fused_decode_supported
    c = DECODE_CELL
    if fused_decode_supported(
            (1, c["heads"], c["seq"], c["feat"] // c["heads"]),
            c["heads"], c["feat"], itemsize=2, weight_itemsize=1):
        ms8 = decode_cell(reps=5, int8=True) * 1e3
        emit("gpt_decode_int8_ms_per_token", ms8, "ms/token",
             R4_DECODE_MS_PER_TOKEN / ms8)
    else:
        print("bench_decode: int8 fused path unavailable here; "
              "skipping the int8 line", file=sys.stderr)


# the serving cell's geometry + trace — single source so the served and
# sequential passes cannot drift onto different request sets
SERVE_CELL = dict(layers=12, heads=12, feat=768, seq=512, vocab=256,
                  slots=8, n_requests=32, mean_gap_ms=5.0, seed=0)


def serve_trace(cell=None):
    """Seeded synthetic open-loop arrival trace: [(gap_s, prompt,
    max_tokens)] — mixed prompt/generation lengths so short requests can
    only win by interleaving, Poisson inter-arrivals submitted on
    schedule regardless of completions (open loop: the arrival process
    does not wait for the server, so queue wait shows up in TTFT)."""
    c = cell or SERVE_CELL
    rs = np.random.RandomState(c["seed"])
    lens = rs.choice([8, 16, 32], c["n_requests"])
    maxt = rs.choice([32, 64], c["n_requests"])
    gaps = rs.exponential(c["mean_gap_ms"] / 1e3, c["n_requests"])
    return [(float(g), rs.randint(0, c["vocab"], (int(l),)).astype(np.int32),
             int(m)) for g, l, m in zip(gaps, lens, maxt)]


def bench_serve():
    """Continuous-batching serving cell (round 7, doc/serving.md): an
    85M-geometry model served by the slot scheduler under the open-loop
    trace above. Emits steady-state aggregate tokens/s and p95 TTFT
    (queue wait included), plus the wall-clock ratio against the SAME
    trace generated one-at-a-time through gpt_decode — the offline
    path's best case (fused kernel, no arrival gaps): > 1.0 means the
    scheduler's slot interleaving beats request-serial decode even
    giving the baseline its fastest kernel. Both passes are warmed so
    compile time is excluded. Since round 9 the server runs its current
    DEFAULTS — chunked prefill + prefix cache — so this line tracks the
    shipped configuration (the r7/r8 recorded numbers were the
    whole-prompt path; doc/serving.md notes the switch), and the
    explicit chunked-vs-whole comparison lives in
    bench_serve_prefill_heavy."""
    import jax
    from cxxnet_tpu.models.gpt import GPTConfig, gpt_decode, gpt_init

    c = SERVE_CELL
    cfg = GPTConfig(vocab_size=c["vocab"], seq_len=c["seq"],
                    n_layer=c["layers"], n_head=c["heads"], feat=c["feat"],
                    n_microbatch=1, dtype="bfloat16")
    params = gpt_init(jax.random.PRNGKey(0), cfg)
    trace = serve_trace(c)

    serve_wall, m_ = run_serve_trace(cfg, params, trace, slots=c["slots"],
                                     queue=c["n_requests"])
    emit("serve_tokens_per_sec", m_["tokens_generated"] / serve_wall,
         "tokens/sec", batch_efficiency=round(m_["batch_efficiency"], 3))
    emit("serve_p95_ttft_ms", m_["ttft_ms"]["p95"], "ms")

    # sequential baseline: the same request set, one at a time, through
    # the offline decode (its per-signature programs warmed first)
    for _ in range(2):
        t0 = time.perf_counter()
        for _, p, m in trace:
            np.asarray(gpt_decode(params, jax.numpy.asarray(p)[None], m,
                                  cfg))
        seq_wall = time.perf_counter() - t0     # second pass is warm
    emit("serve_vs_sequential", seq_wall / serve_wall, "ratio")


# the prefill-heavy serving cell: every prompt = one shared system-style
# prefix + a short per-request suffix, short generations — the regime
# where prefill (not decode) dominates and identical prefixes repeat.
# Single source for both the chunked+prefix pass and the whole-prompt
# baseline so they cannot drift onto different request sets.
PREFIX_CELL = dict(layers=12, heads=12, feat=768, seq=512, vocab=256,
                   slots=8, n_requests=32, mean_gap_ms=5.0, seed=1,
                   prefix_len=320, suffix=(8, 16, 24), max_new=(8, 16),
                   chunk=64, budget=4)
# budget 4 (not the serving default of 1): this cell is prefill-heavy by
# construction, so trading a little inter-token latency for prefill
# throughput is the right operating point — the CPU-scaled cell measured
# p95 TTFT ~10% worse at budget 1 (doc/serving.md records the sweep)


def serve_prefix_trace(cell=None):
    """Seeded prefill-heavy shared-prefix trace: [(gap_s, prompt,
    max_tokens)] with Poisson open-loop arrivals (serve_trace's process)
    — prompts share the first ``prefix_len`` tokens, so after one
    request retires the rest can restore that prefix from the KV trie
    instead of recomputing it."""
    c = cell or PREFIX_CELL
    rs = np.random.RandomState(c["seed"])
    shared = rs.randint(0, c["vocab"], (c["prefix_len"],)).astype(np.int32)
    suff = rs.choice(list(c["suffix"]), c["n_requests"])
    maxt = rs.choice(list(c["max_new"]), c["n_requests"])
    gaps = rs.exponential(c["mean_gap_ms"] / 1e3, c["n_requests"])
    return [(float(g),
             np.concatenate([shared,
                             rs.randint(0, c["vocab"],
                                        (int(s),)).astype(np.int32)]),
             int(m)) for g, s, m in zip(gaps, suff, maxt)]


def run_serve_trace(cfg, params, trace, replicas=1, **server_kw):
    """One warmed open-loop pass of ``trace`` through an InferenceServer
    (or, with ``replicas`` > 1, a ServeRouter over that many engine
    replicas) built with ``server_kw``; returns (wall seconds,
    metrics). The warm pass compiles every program AND fills the
    prefix cache, so the measured pass sees the steady state."""
    from cxxnet_tpu.serve import InferenceServer, ServeRouter

    if replicas > 1:
        srv = ServeRouter(cfg, params, replicas=replicas, **server_kw)
    else:
        srv = InferenceServer(cfg, params, **server_kw)
    try:
        for h in [srv.submit(p, max_tokens=m) for _, p, m in trace]:
            srv.result(h)
        srv.reset_metrics()
        t0 = time.perf_counter()
        handles = []
        for gap, p, m in trace:                 # open loop: submit on
            time.sleep(gap)                     # schedule, never wait
            handles.append(srv.submit(p, max_tokens=m))
        for h in handles:
            srv.result(h)
        wall = time.perf_counter() - t0
        metrics = srv.metrics()
    finally:
        srv.shutdown()
    return wall, metrics


def bench_serve_prefill_heavy():
    """Chunked prefill + shared-prefix KV reuse under the prefill-heavy
    trace (round 9, doc/serving.md): emits the rate of prompt tokens
    served straight from the prefix cache, and p95 TTFT with
    vs_baseline against the SAME trace through the legacy whole-prompt
    prefill path (serve_prefill_chunk=0, no prefix cache) — the
    configuration this PR replaced as the default."""
    import jax
    from cxxnet_tpu.models.gpt import GPTConfig, gpt_init

    c = PREFIX_CELL
    cfg = GPTConfig(vocab_size=c["vocab"], seq_len=c["seq"],
                    n_layer=c["layers"], n_head=c["heads"], feat=c["feat"],
                    n_microbatch=1, dtype="bfloat16")
    params = gpt_init(jax.random.PRNGKey(0), cfg)
    trace = serve_prefix_trace(c)
    kw = dict(slots=c["slots"], queue=c["n_requests"])
    wall, m_ = run_serve_trace(cfg, params, trace,
                               prefill_chunk=c["chunk"],
                               prefill_budget=c["budget"], **kw)
    _, m0 = run_serve_trace(cfg, params, trace, prefill_chunk=0,
                            prefix_mb=0.0, **kw)
    emit("serve_prefix_hit_tokens_per_sec",
         m_["prefix_cache"]["hit_tokens"] / wall, "tokens/sec",
         hit_rate=round(m_["prefix_hit_rate"], 3),
         prefill_chunks_per_req=round(m_["prefill_chunks_per_req"], 2))
    emit("serve_p95_ttft_ms_prefill_heavy", m_["ttft_ms"]["p95"], "ms",
         m0["ttft_ms"]["p95"] / max(m_["ttft_ms"]["p95"], 1e-9),
         whole_prefill_p95_ms=round(m0["ttft_ms"]["p95"], 1))


def bench_serve_paged():
    """Paged KV cache cell (round 13, doc/serving.md "Paged KV cache"):
    the PREFIX_CELL shared-prefix Poisson trace at 4x the request
    concurrency of ``slots``, served under the SAME KV MiB budget by
    (a) the dense slot pool — ``slots`` rows, each pinning a full
    chunk-padded row — and (b) the paged engine with 4x the slots over
    a block pool of the same bytes (shared prefix blocks held once,
    zero-copy, preemption/swap under pressure). Emits
    ``serve_tokens_per_mib`` (steady-state tokens/s per KV MiB;
    vs_baseline = paged / dense — the capacity-efficiency headline,
    acceptance gate >= 1.5) and ``serve_p95_ttft_ms_paged``
    (vs_baseline = dense p95 / paged p95)."""
    import jax
    from cxxnet_tpu.models.gpt import GPTConfig, gpt_init

    c = dict(PREFIX_CELL)
    c["n_requests"] = 4 * c["slots"]
    cfg = GPTConfig(vocab_size=c["vocab"], seq_len=c["seq"],
                    n_layer=c["layers"], n_head=c["heads"], feat=c["feat"],
                    n_microbatch=1, dtype="bfloat16")
    params = gpt_init(jax.random.PRNGKey(0), cfg)
    trace = serve_prefix_trace(c)
    # the shared TOTAL KV budget: what `slots` dense rows pin plus the
    # dense arm's prefix-trie copies (its trie is memory ON TOP of the
    # slot pool; the paged trie lives INSIDE the block pool, so the
    # paged arm gets the same total as one kv_mb pool)
    row_len = (c["seq"] + c["chunk"] - 1) // c["chunk"] * c["chunk"]
    hd = c["feat"] // c["heads"]
    prefix_mb = 16.0
    mib = (2 * c["layers"] * c["slots"] * c["heads"] * row_len * hd * 2
           / 2.0 ** 20) + prefix_mb
    kw = dict(queue=c["n_requests"], prefill_chunk=c["chunk"],
              prefill_budget=c["budget"], prefix_mb=prefix_mb)
    wall_d, md = run_serve_trace(cfg, params, trace, slots=c["slots"],
                                 paged=False, **kw)
    wall_p, mp = run_serve_trace(cfg, params, trace,
                                 slots=4 * c["slots"], kv_mb=mib, **kw)
    tpm_d = md["tokens_generated"] / wall_d / mib
    tpm_p = mp["tokens_generated"] / wall_p / mib
    emit("serve_tokens_per_mib", tpm_p, "tokens/sec/MiB",
         tpm_p / max(tpm_d, 1e-9),
         dense_tokens_per_mib=round(tpm_d, 4), kv_mib=round(mib, 1),
         paged_slots=4 * c["slots"], dense_slots=c["slots"],
         swaps_out=mp["paged"]["swaps_out"],
         cow_faults=mp["paged"]["cow_faults"])
    emit("serve_p95_ttft_ms_paged", mp["ttft_ms"]["p95"], "ms",
         md["ttft_ms"]["p95"] / max(mp["ttft_ms"]["p95"], 1e-9),
         dense_p95_ms=round(md["ttft_ms"]["p95"], 1))


def bench_serve_fused():
    """Fused paged-attention cell (round 16, doc/serving.md "Fused
    paged attention"): the SAME shared-prefix Poisson trace as
    bench_serve_paged's paged arm, served twice by the paged engine —
    ``serve_fused_attn=1`` (the default: fused Pallas block-table-walk
    tick/verify wherever the backend supports the kernel) vs
    ``serve_fused_attn=0`` (the XLA gather formulation, the
    bit-reference). Emits ``serve_tokens_per_sec_fused`` with
    vs_baseline = fused / gather. On a TPU the fused arm must be >= the
    gather arm (the kernel removes the gathered-cache HBM round trip);
    on backends where the kernel is unsupported both arms resolve to
    gather (``fused_active: false``) and the ratio pins the off-switch
    as a true no-op (~1.0). Both arms run the devprof live sampler so
    ``cxn_mfu{fn=serve_tick}`` lands in the roofline trend — reported
    here as the ``mfu_serve_tick`` attribute."""
    import jax
    from cxxnet_tpu.models.gpt import GPTConfig, gpt_init
    from cxxnet_tpu.obs.metrics import Registry

    c = dict(PREFIX_CELL)
    c["n_requests"] = 4 * c["slots"]
    cfg = GPTConfig(vocab_size=c["vocab"], seq_len=c["seq"],
                    n_layer=c["layers"], n_head=c["heads"], feat=c["feat"],
                    n_microbatch=1, dtype="bfloat16")
    params = gpt_init(jax.random.PRNGKey(0), cfg)
    trace = serve_prefix_trace(c)
    kw = dict(queue=c["n_requests"], prefill_chunk=c["chunk"],
              prefill_budget=c["budget"], prefix_mb=16.0,
              slots=c["slots"], prof_every=16)
    reg_f = Registry()
    wall_f, mf = run_serve_trace(cfg, params, trace, fused_attn=True,
                                 registry=reg_f, **kw)
    wall_g, mg = run_serve_trace(cfg, params, trace, fused_attn=False,
                                 **kw)
    tps_f = mf["tokens_generated"] / wall_f
    tps_g = mg["tokens_generated"] / wall_g
    mfu = reg_f.snapshot().get('cxn_mfu{fn="serve_tick"}')
    emit("serve_tokens_per_sec_fused", tps_f, "tokens/sec",
         tps_f / max(tps_g, 1e-9),
         fused_active=bool(mf["paged"]["fused_attn"]),
         gather_tokens_per_sec=round(tps_g, 1),
         mfu_serve_tick=(round(mfu, 6) if mfu is not None else None))


# the long-context streaming cell: prompts deep enough that a row's
# whole KV image is a real VMEM liability. head_dim 64 keeps the
# geometry one a real TPU would fuse; the cell CLAMPS the resident
# VMEM gate so its rows cross into the streaming formulation — the
# arm under test is the online-softmax accumulation path, exactly
# what a production-sized long-context row (past the real 12 MiB
# gate) resolves to.
LONGCTX_CELL = dict(layers=2, heads=4, feat=256, seq=512, vocab=256,
                    slots=4, n_requests=12, mean_gap_ms=5.0, seed=3,
                    prefix_len=384, suffix=(8, 16), max_new=(8, 16),
                    chunk=64, budget=4)


def bench_serve_longctx():
    """Long-context streaming-attention cell (doc/serving.md
    "Streaming fused attention"): a long-prompt shared-prefix Poisson
    trace whose rows are pushed past the resident VMEM gate (the cell
    clamps ``_PAGED_RESIDENT_VMEM`` to an eighth of a row image, the
    CI-priced stand-in for a production row blowing the real 12 MiB
    budget), served ``serve_fused_attn=1`` vs ``0``. Wherever the
    Pallas kernel arms, the fused arm resolves the STREAMING
    formulation — rows that round 16's resident kernel would have
    dropped back to gather stay fused — and
    ``serve_tokens_per_sec_longctx`` records streaming / gather. On
    backends without the kernel both arms resolve gather and the
    ratio pins the off-switch no-op (~1.0), same contract as the
    resident fused cell."""
    import jax
    from cxxnet_tpu.models.gpt import GPTConfig, gpt_init
    from cxxnet_tpu.ops import pallas_kernels as pk

    c = dict(LONGCTX_CELL)
    cfg = GPTConfig(vocab_size=c["vocab"], seq_len=c["seq"],
                    n_layer=c["layers"], n_head=c["heads"], feat=c["feat"],
                    n_microbatch=1, dtype="bfloat16")
    params = gpt_init(jax.random.PRNGKey(0), cfg)
    trace = serve_prefix_trace(c)
    kw = dict(queue=c["n_requests"], prefill_chunk=c["chunk"],
              prefill_budget=c["budget"], prefix_mb=8.0,
              slots=c["slots"])
    hd = c["feat"] // c["heads"]
    row_vmem = pk._paged_row_vmem(c["heads"], c["seq"] // c["chunk"],
                                  c["chunk"], hd, 2)
    old_gate = pk._PAGED_RESIDENT_VMEM
    pk._PAGED_RESIDENT_VMEM = row_vmem // 8
    try:
        wall_s, ms_ = run_serve_trace(cfg, params, trace,
                                      fused_attn=True, **kw)
    finally:
        pk._PAGED_RESIDENT_VMEM = old_gate
    wall_g, mg = run_serve_trace(cfg, params, trace, fused_attn=False,
                                 **kw)
    tps_s = ms_["tokens_generated"] / wall_s
    tps_g = mg["tokens_generated"] / wall_g
    emit("serve_tokens_per_sec_longctx", tps_s, "tokens/sec",
         tps_s / max(tps_g, 1e-9),
         formulation=ms_["paged"]["fused_formulation"] or "gather",
         gather_tokens_per_sec=round(tps_g, 1),
         prompt_len=c["prefix_len"] + max(c["suffix"]))


def bench_serve_autotune():
    """Geometry-autotune cell (doc/performance.md "Geometry
    autotuning"): the ``task=autotune`` sweep run in-process on the
    replication cell's geometry — every ``serve_block_size`` divisor
    of the prefill chunk built as a real engine and its AOT decode
    tick timed on zero-filled inputs — then the SAME trace served at
    the default geometry vs ``serve_block_size=auto`` loading the
    persisted winner. Emits ``autotune_wall_ms`` (the once-per-fleet
    tuning cost; the executables it compiled persist through the AOT
    cache, so replicas pay none of it) and
    ``serve_tokens_per_sec_tuned`` with vs_baseline = tuned / default
    — >= 1.0 when the sweep finds a better block size, ~1.0 when the
    default was already the winner (the honest no-win case)."""
    import dataclasses
    import tempfile

    from cxxnet_tpu.analysis import aot_cache as aot_mod
    from cxxnet_tpu.obs import devprof
    from cxxnet_tpu.serve.engine import DecodeEngine, auto_num_blocks

    c, cfg, params = _repl_model()
    trace = _repl_trace(c)
    chunk = min(c["chunk"], cfg.seq_len)
    # a rig that exports CXN_AOT_CACHE would warm the default arm from
    # a previous run's executables; isolate the cell like the
    # cold-start one does
    env_cache = os.environ.pop("CXN_AOT_CACHE", None)
    try:
        with tempfile.TemporaryDirectory() as d:
            cache = aot_mod.get_cache(d)
            t0 = time.perf_counter()
            rows = []
            for bs in [x for x in range(1, chunk + 1) if chunk % x == 0]:
                nb = auto_num_blocks(cfg, c["slots"], chunk,
                                     block_size=bs)
                eng = DecodeEngine(cfg, params, slots=c["slots"],
                                   prefill_chunk=chunk, num_blocks=nb,
                                   block_size=bs, aot=cache)
                table = devprof.profile_engine(eng, time_reps=3)
                rows.append((table.get("serve_tick").measured_s, bs,
                             eng.fused_formulation or "gather"))
                eng.close()
            tick_s, win_bs, form = min(rows)
            wall_ms = (time.perf_counter() - t0) * 1e3
            comp = aot_mod.tuned_components(
                aot_mod.config_hash(dataclasses.astuple(cfg)), chunk,
                "", 1)
            cache.store_tuned(comp, {"block_size": win_bs,
                                     "formulation": form,
                                     "tick_ms": tick_s * 1e3})
            emit("autotune_wall_ms", wall_ms, "ms",
                 candidates=len(rows), winner_block_size=win_bs,
                 winner_tick_ms=round(tick_s * 1e3, 3))
            kw = dict(slots=c["slots"], queue=c["n_requests"],
                      prefill_chunk=chunk)
            wall_d, md = run_serve_trace(cfg, params, trace, **kw)
            wall_t, mt = run_serve_trace(cfg, params, trace,
                                         block_size=-1, aot_cache=d,
                                         **kw)
            tps_d = md["tokens_generated"] / wall_d
            tps_t = mt["tokens_generated"] / wall_t
            emit("serve_tokens_per_sec_tuned", tps_t, "tokens/sec",
                 tps_t / max(tps_d, 1e-9),
                 tuned_block_size=mt["paged"]["block_size"],
                 default_block_size=md["paged"]["block_size"],
                 default_tokens_per_sec=round(tps_d, 1))
    finally:
        if env_cache is not None:
            os.environ["CXN_AOT_CACHE"] = env_cache


# the quantized-serving cell's geometry + trace: a shared-prefix
# prefill-heavy mix like PREFIX_CELL but small enough that the
# deliberately memory-starved bf16 arm's preempt/swap churn stays
# CI-priced (the 85M geometry measured multi-minute swap storms on the
# 1-core rig); head_dim 64 keeps the int8 scale overhead realistic
# (~1.9x blocks per MiB, not the tiny-model 1.6x)
INT8_CELL = dict(layers=4, heads=4, feat=256, seq=256, vocab=256,
                 slots=8, n_requests=16, mean_gap_ms=2.0, seed=1,
                 prefix_len=160, suffix=(8, 16, 24), max_new=(8, 16),
                 chunk=32, budget=4)


def bench_serve_int8():
    """Quantized serving cell (doc/serving.md "Quantized serving"): the
    paged shared-prefix Poisson trace under a deliberately TIGHT
    ``serve_kv_mb`` budget, served twice at the SAME budget — the bf16
    pool vs the per-block-scaled int8 pool with int8 weight streaming.
    The int8 block itemsize buys ~1.9x the blocks for the same MiB, so
    the bf16 arm lives in the preempt/swap regime while the int8 arm
    holds its working set — the capacity win compounds with paged KV's
    measured 1.73x exactly as ROADMAP item 3 predicted. Emits
    ``serve_tokens_per_mib_int8`` (vs_baseline = int8 / bf16 at equal
    MiB; acceptance gate >= 1.5 on the CI rig) and
    ``gpt_decode_spec_int8_ms_per_token`` — speculative decode WITH
    int8 weights, the combination ``gpt_decode`` used to reject
    (vs_baseline = the same speculative run at full precision; the
    halved weight working set pays even on the CPU rig — 1.23x
    recorded — and the full HBM-bandwidth win is a TPU rig's to
    record)."""
    import jax
    from cxxnet_tpu.models.gpt import GPTConfig, gpt_decode, gpt_init

    c = dict(INT8_CELL)
    cfg = GPTConfig(vocab_size=c["vocab"], seq_len=c["seq"],
                    n_layer=c["layers"], n_head=c["heads"], feat=c["feat"],
                    n_microbatch=1, dtype="bfloat16")
    params = gpt_init(jax.random.PRNGKey(0), cfg)
    trace = serve_prefix_trace(c)
    # the tight shared budget: ~1.75 bf16 rows' worth. With the
    # 5-block shared prefix held once in the trie, the bf16 arm's 14
    # blocks admit ~4 concurrent rows (marginal cost ~2 blocks each)
    # while the int8 arm's ~26 blocks keep the whole 8-slot pool
    # decoding every tick — the capacity ratio IS the throughput ratio
    # on a batched tick. Kept above the 1-row terminal-stall regime on
    # purpose (a pool that cannot hold the live working set at all
    # measures the failure path, not capacity; the 3x-rows sweep
    # measured only 1.13x because nothing starved)
    hd = c["feat"] // c["heads"]
    row_len = (c["seq"] + c["chunk"] - 1) // c["chunk"] * c["chunk"]
    row_mib = (2 * c["layers"] * c["heads"] * row_len * hd * 2) / 2.0 ** 20
    mib = 1.75 * row_mib
    kw = dict(queue=c["n_requests"], prefill_chunk=c["chunk"],
              prefill_budget=c["budget"], prefix_mb=16.0,
              slots=c["slots"], kv_mb=mib)
    wall_b, mb_ = run_serve_trace(cfg, params, trace, **kw)
    wall_q, mq = run_serve_trace(cfg, params, trace, kv_dtype="int8",
                                 int8_weights=True, **kw)
    tpm_b = mb_["tokens_generated"] / wall_b / mib
    tpm_q = mq["tokens_generated"] / wall_q / mib
    emit("serve_tokens_per_mib_int8", tpm_q, "tokens/sec/MiB",
         tpm_q / max(tpm_b, 1e-9),
         bf16_tokens_per_mib=round(tpm_b, 4), kv_mib=round(mib, 1),
         bf16_blocks=mb_["paged"]["num_blocks"],
         int8_blocks=mq["paged"]["num_blocks"],
         bf16_swaps_out=mb_["paged"]["swaps_out"],
         int8_swaps_out=mq["paged"]["swaps_out"])

    # speculative + int8 weights, offline: the decode-spec cell's exact
    # prompt/drafter, both arms measured in this run
    d, s = DECODE_CELL, SPEC_CELL
    dcfg = GPTConfig(vocab_size=256, seq_len=d["seq"],
                     n_layer=d["layers"], n_head=d["heads"],
                     feat=d["feat"], n_microbatch=1, dtype="bfloat16")
    dparams = gpt_init(jax.random.PRNGKey(0), dcfg)
    rs = np.random.RandomState(0)
    seed = jax.numpy.asarray(rs.randint(0, 256, (1, 8)).astype(np.int32))
    warm = np.asarray(gpt_decode(dparams, seed, s["warm_tokens"], dcfg))[0]
    prompt = jax.numpy.asarray(
        warm[None, -s["prompt_len"]:].astype(np.int32))
    # half the decode-spec cell's horizon: the per-token figure is
    # stable well before 256 tokens, and this cell runs BOTH arms
    max_new = min(s["max_new"] // 2, d["seq"] - s["prompt_len"])

    def run(int8):
        sp = {"mode": "ngram", "spec_len": s["spec_len"], "stats": {}}
        np.asarray(gpt_decode(dparams, prompt, max_new, dcfg,
                              speculative=sp, int8_weights=int8))
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            np.asarray(gpt_decode(dparams, prompt, max_new, dcfg,
                                  speculative=sp, int8_weights=int8))
            best = min(best, time.perf_counter() - t0)
        return best / max_new * 1e3, sp["stats"]

    bf_ms, _ = run(False)
    i8_ms, st = run(True)
    emit("gpt_decode_spec_int8_ms_per_token", i8_ms, "ms/token",
         bf_ms / i8_ms,
         accept_rate=round(st["accept_rate"], 3),
         spec_bf16_ms_per_token=round(bf_ms, 4))


# the int4 weight-streaming cell's geometry: weight-heavy on purpose —
# feat 640 puts ~20 MiB of int8 (~10 MiB int4-packed) block weights
# against a ~3 MiB KV budget, so the device working set (KV pool +
# resident weight pool) is weight-dominated and the packed-nibble
# pool's 2x-under-int8 / 4x-under-bf16 shrink shows up in the
# denominator the way HBM sees it. Short prompts keep the live KV
# working set INSIDE the budget (no preempt/swap storms): unlike the
# int8 cell this one prices the weight stream, not KV capacity, and
# the swap regime's wall-clock noise would drown a weight-pool ratio.
# All three arms share the bf16 KV pool at the SAME serve_kv_mb so the
# block-capacity schedule is identical and ONLY the weight stream
# differs between arms.
INT4_CELL = dict(layers=4, heads=4, feat=640, seq=128, vocab=64,
                 slots=4, n_requests=12, mean_gap_ms=2.0, seed=1,
                 prefix_len=32, suffix=(4, 8, 12), max_new=(8, 16),
                 chunk=32, budget=4)


def bench_serve_int4():
    """Int4 weight-streaming cell (doc/serving.md "Int4 weights"): the
    shared-prefix Poisson trace served three times at the SAME
    ``serve_kv_mb`` budget — bf16 weights, int8 weights, and packed
    int4 weights (per-out-column scales, ``serve_int4_group=0``) — with
    the metric pricing the whole device working set: steady-state
    tokens/s per MiB of (KV pool + resident weight pool), the weight
    pool read from the device-memory ledger so the int4 arm is priced
    at its PACKED bytes. Emits ``serve_tokens_per_mib_int4``
    (vs_baseline = int4 / int8 at equal KV MiB; acceptance gate >= 1.5
    — the packed pool halves the int8 arm's weight bytes while the
    fused dequant-matmul keeps the unpack off HBM) and
    ``gpt_decode_int4_ms_per_token`` — the offline DECODE_CELL decode
    with int4 weight streaming (vs_baseline = the same run at full
    precision; on the CPU rig this pins the dequant machinery's
    overhead, the HBM-bandwidth win being a TPU rig's to record)."""
    import jax
    from cxxnet_tpu.models.gpt import GPTConfig, gpt_decode, gpt_init

    c = dict(INT4_CELL)
    cfg = GPTConfig(vocab_size=c["vocab"], seq_len=c["seq"],
                    n_layer=c["layers"], n_head=c["heads"], feat=c["feat"],
                    n_microbatch=1, dtype="bfloat16")
    params = gpt_init(jax.random.PRNGKey(0), cfg)
    trace = serve_prefix_trace(c)
    # the shared budget: exactly the live working set — one shared
    # prefix block plus two private blocks per slot (suffix + generated
    # tokens span at most two block windows). Every arm fits, nothing
    # swaps, and the tokens/s numerator stays in the low-noise regime;
    # the denominator does the discriminating.
    hd = c["feat"] // c["heads"]
    block_mib = (2 * c["layers"] * c["heads"] * c["chunk"] * hd * 2) \
        / 2.0 ** 20
    mib = (1 + 2 * c["slots"]) * block_mib
    kw = dict(queue=c["n_requests"], prefill_chunk=c["chunk"],
              prefill_budget=c["budget"], prefix_mb=16.0,
              slots=c["slots"], kv_mb=mib)

    def arm(**qkw):
        wall, m = run_serve_trace(cfg, params, trace, **kw, **qkw)
        wmib = m["device_bytes"]["pools"]["params"] / 2.0 ** 20
        return m["tokens_generated"] / wall / (mib + wmib), wmib, m

    tpm_b, wmib_b, _ = arm()
    tpm_8, wmib_8, _ = arm(int8_weights=True)
    tpm_4, wmib_4, m4 = arm(int4_weights=True, int4_group=0)
    emit("serve_tokens_per_mib_int4", tpm_4, "tokens/sec/MiB",
         tpm_4 / max(tpm_8, 1e-9),
         int8_tokens_per_mib=round(tpm_8, 4),
         bf16_tokens_per_mib=round(tpm_b, 4), kv_mib=round(mib, 1),
         weight_mib_bf16=round(wmib_b, 2),
         weight_mib_int8=round(wmib_8, 2),
         weight_mib_int4=round(wmib_4, 2),
         int4_formulation=m4["int4_formulation"] or "xla_ref")

    # offline int4 decode: the decode cell's exact prompt, both arms in
    # this run; per-column scales keep the CPU reference dequant a
    # single unpack + dot per weight (the grouped kernel path is the
    # TPU rig's measurement)
    d = DECODE_CELL
    dcfg = GPTConfig(vocab_size=256, seq_len=d["seq"],
                     n_layer=d["layers"], n_head=d["heads"],
                     feat=d["feat"], n_microbatch=1, dtype="bfloat16")
    dparams = gpt_init(jax.random.PRNGKey(0), dcfg)
    rs = np.random.RandomState(0)
    prompt = jax.numpy.asarray(
        rs.randint(0, 256, (1, d["prompt_len"])).astype(np.int32))
    max_new = 64

    def run(int4):
        qkw = dict(int4_weights=int4, int4_group=0) if int4 else {}
        np.asarray(gpt_decode(dparams, prompt, max_new, dcfg, **qkw))
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            np.asarray(gpt_decode(dparams, prompt, max_new, dcfg, **qkw))
            best = min(best, time.perf_counter() - t0)
        return best / max_new * 1e3

    bf_ms = run(False)
    i4_ms = run(True)
    emit("gpt_decode_int4_ms_per_token", i4_ms, "ms/token",
         bf_ms / i4_ms, bf16_ms_per_token=round(bf_ms, 4))


LORA_CELL = dict(layers=2, heads=4, feat=64, seq=160, vocab=256,
                 slots=8, n_requests=16, n_adapters=16, rank=4,
                 mean_gap_ms=1.0, seed=23, chunk=16, max_new=(16, 24))


def bench_serve_lora():
    """Batched multi-LoRA cell (doc/serving.md "Batched multi-LoRA"): a
    mixed 16-adapter Poisson trace served two ways through the SAME
    armed stack. The batched arm holds every adapter resident in the
    paged pool and serves the whole mixed population in one decode tick
    per step (one traced program, per-row adapter ids, ragged grouped
    delta). The swap baseline models the classic one-adapter-at-a-time
    engine: a 2-slot pool (base + one adapter) served group-by-group —
    drain the batch, swap the next adapter in, re-admit — which is what
    serving N adapters costs without per-row dispatch. Emits
    ``serve_tokens_per_sec_lora_mixed`` (vs_baseline = batched/swap;
    acceptance gate >= 2 — every request names its OWN adapter, so the
    swap arm's ticks run one row each while the batched arm keeps all
    8 slots full) and ``serve_lora_vs_swap`` (the ratio itself), with
    the batched arm's pool counters as extras."""
    import jax
    from cxxnet_tpu.models.gpt import GPTConfig, gpt_init
    from cxxnet_tpu.serve import InferenceServer
    from cxxnet_tpu.serve.lora import make_adapter

    c = dict(LORA_CELL)
    cfg = GPTConfig(vocab_size=c["vocab"], seq_len=c["seq"],
                    n_layer=c["layers"], n_head=c["heads"], feat=c["feat"],
                    n_microbatch=1)
    params = gpt_init(jax.random.PRNGKey(0), cfg)
    names = ["a%02d" % i for i in range(c["n_adapters"])]
    adapters = {n: make_adapter(cfg, c["rank"], seed=i)
                for i, n in enumerate(names)}
    spec = ";".join("%s:%s.npz" % (n, n) for n in names)

    rs = np.random.RandomState(c["seed"])
    gaps = rs.exponential(c["mean_gap_ms"] / 1e3, c["n_requests"])
    maxt = rs.choice(list(c["max_new"]), c["n_requests"])
    trace = [(float(g),
              rs.randint(0, c["vocab"], (rs.randint(8, 24),))
              .astype(np.int32),
              int(m), names[i % c["n_adapters"]])
             for i, (g, m) in enumerate(zip(gaps, maxt))]

    def arm(batched):
        # pool_mb tiny-but-set clamps the swap arm to the 2-slot floor
        # (base + one adapter): every group change is a host swap-in,
        # exactly the engine the batched pool replaces
        srv = InferenceServer(
            cfg, params, slots=c["slots"], queue=c["n_requests"],
            prefill_chunk=c["chunk"], prefix_mb=4.0, paged=True,
            lora=spec, lora_rank=c["rank"], lora_adapters=adapters,
            lora_pool_mb=(0.0 if batched else 1e-9))
        def one_pass():
            t0 = time.perf_counter()
            if batched:                      # open loop, mixed population
                handles = []
                for gap, p, m, a in trace:
                    time.sleep(gap)
                    handles.append(srv.submit(p, max_tokens=m, adapter=a))
                for h in handles:
                    srv.result(h)
            else:                            # drain between adapter groups
                for name in names:
                    group = [srv.submit(p, max_tokens=m, adapter=a)
                             for _, p, m, a in trace if a == name]
                    for h in group:
                        srv.result(h)
            return time.perf_counter() - t0

        try:
            one_pass()                       # compile + populate the pool
            best = float("inf")
            for _ in range(2):
                srv.reset_metrics()
                wall = one_pass()
                m = srv.metrics()
                best = min(best, wall)
        finally:
            srv.shutdown()
        return m["tokens_generated"] / best, m

    tps_seq, _ = arm(batched=False)
    tps_mix, mm = arm(batched=True)
    ratio = tps_mix / max(tps_seq, 1e-9)
    lp = mm["lora"]
    emit("serve_tokens_per_sec_lora_mixed", tps_mix, "tokens/sec",
         ratio, swap_tokens_per_sec=round(tps_seq, 2),
         pool_hits=lp["hits"], pool_swap_ins=lp["swap_ins"],
         pool_evictions=lp["evictions"], pool_slots=lp["size"],
         adapters=c["n_adapters"], rank=lp["rank"])
    emit("serve_lora_vs_swap", ratio, "x", ratio)


# the sharded/replicated serving cell (round 17, doc/serving.md
# "Sharded & replicated serving"): small geometry — the POINT on a CPU
# rig is exercising the real partitioned programs / router machinery
# end to end and recording honest CPU-scaled ratios, not FLOPs. On this
# rig `nproc` is 1: a single XLA engine already owns the core, so
# neither TP (adds collectives + resharding on one core) nor in-process
# replication (two schedulers sharing one core) can beat 1.0x wall-
# clock — the recorded vs_baseline ratios pin the MACHINERY'S overhead
# honestly, while the multi-chip win (1/tp KV bytes per chip, N cores
# serving N replicas) is the TPU rig's to record. What replication DOES
# win on any rig is availability, so the cell also measures goodput
# under a chaos-killed engine: the router replays the dead replica's
# requests on the survivor (completed fraction ~1.0) while the single
# engine fails every in-flight + later request.
REPL_CELL = dict(layers=2, heads=4, feat=64, seq=128, vocab=256,
                 slots=2, n_requests=24, mean_gap_ms=1.0, seed=11,
                 chunk=16, max_new=(24, 48))


def _repl_model():
    import jax
    from cxxnet_tpu.models.gpt import GPTConfig, gpt_init

    c = REPL_CELL
    cfg = GPTConfig(vocab_size=c["vocab"], seq_len=c["seq"],
                    n_layer=c["layers"], n_head=c["heads"],
                    feat=c["feat"], n_microbatch=1)
    return c, cfg, gpt_init(jax.random.PRNGKey(0), cfg)


def _repl_trace(c):
    rs = np.random.RandomState(c["seed"])
    lens = rs.choice([8, 16], c["n_requests"])
    maxt = rs.choice(list(c["max_new"]), c["n_requests"])
    gaps = rs.exponential(c["mean_gap_ms"] / 1e3, c["n_requests"])
    return [(float(g),
             rs.randint(0, c["vocab"], (int(l),)).astype(np.int32),
             int(m)) for g, l, m in zip(gaps, lens, maxt)]


def bench_serve_sharded():
    """TP-sharded serving cell: the same Poisson trace served by the
    single-device engine and by the tp=2 gather-form TP engine (KV
    pool head-sharded over a 2-device mesh — on CPU, two forced host
    devices). Emits ``serve_tokens_per_sec_tp2`` with vs_baseline =
    tp2 / tp1; tokens are bit-identical by construction (the identity
    the test suite pins), so the ratio is pure partitioning overhead
    on this rig and pure memory-per-chip win on a real one."""
    import jax

    c, cfg, params = _repl_model()
    trace = _repl_trace(c)
    kw = dict(slots=c["slots"], queue=c["n_requests"],
              prefill_chunk=c["chunk"])
    wall_1, m1 = run_serve_trace(cfg, params, trace, **kw)
    tps1 = m1["tokens_generated"] / wall_1
    if len(jax.devices()) < 2:
        emit("serve_tokens_per_sec_tp2", tps1, "tokens/sec", 1.0,
             skipped="needs >= 2 devices")
        return
    wall_2, m2 = run_serve_trace(cfg, params, trace, tp=2, **kw)
    tps2 = m2["tokens_generated"] / wall_2
    emit("serve_tokens_per_sec_tp2", tps2, "tokens/sec",
         tps2 / max(tps1, 1e-9),
         tp1_tokens_per_sec=round(tps1, 1),
         kv_bytes_per_shard=m2["kv_cache_bytes"] // 2)


def bench_serve_replicated():
    """Replicated-router cell: the trace served by ONE engine vs TWO
    engine replicas behind the prefix/health router. Emits
    ``serve_tokens_per_sec_replicated`` (vs_baseline = router / single
    — the aggregate-throughput headline, ~Nx on an N-device rig, pinned
    honest on shared cores) and ``serve_goodput_replicated_kill``: the
    completed-request fraction when an engine is chaos-killed
    mid-trace (restart budget 0) — the router replays the dead
    replica's requests on the survivor, the single engine fails
    everything from the kill on. vs_baseline there = router completed /
    single completed, the availability win replication exists for."""
    c, cfg, params = _repl_model()
    trace = _repl_trace(c)
    kw = dict(slots=c["slots"], queue=c["n_requests"],
              prefill_chunk=c["chunk"])
    wall_1, m1 = run_serve_trace(cfg, params, trace, **kw)
    tps1 = m1["tokens_generated"] / wall_1
    wall_r, mr = run_serve_trace(cfg, params, trace, replicas=2, **kw)
    tps_r = mr["tokens_generated"] / wall_r
    emit("serve_tokens_per_sec_replicated", tps_r, "tokens/sec",
         tps_r / max(tps1, 1e-9),
         single_tokens_per_sec=round(tps1, 1),
         routed=mr["routed"], failovers=mr["failovers"])

    # availability under a mid-trace engine kill (chaos tick_raise@N,
    # restart budget 0): count completed requests, not tokens — a dead
    # engine's unfinished + rejected requests are the outage
    from cxxnet_tpu.serve import (EngineFailedError, InferenceServer,
                                  QueueFullError, ServeRouter)

    def goodput(server):
        ok = 0
        handles = []
        try:
            for gap, p, m in trace:
                time.sleep(gap)
                try:
                    handles.append(server.submit(p, max_tokens=m))
                except (EngineFailedError, QueueFullError):
                    pass
            for h in handles:
                if server.result(h, timeout=600).status == "ok":
                    ok += 1
        finally:
            server.shutdown(drain=False)
        return ok / float(len(trace))

    kill = "tick_raise@40"
    g_single = goodput(InferenceServer(cfg, params, chaos=kill,
                                       max_restarts=0, **kw))
    g_router = goodput(ServeRouter(cfg, params, replicas=2,
                                   chaos=(kill, ""), max_restarts=0,
                                   **kw))
    emit("serve_goodput_replicated_kill", g_router, "fraction",
         g_router / max(g_single, 1e-9),
         single_goodput=round(g_single, 3))


def bench_serve_fleet():
    """Cross-process fleet cell (doc/serving.md "Disaggregated
    fleet"): the REPL_CELL trace served by the in-process 2-replica
    router vs a 1-prefill + 2-decode worker-process fleet behind the
    RPC router — every request chunk-prefills on the prefill tier and
    its checksummed KV record migrates over a socket to a decode
    worker. Emits ``serve_tokens_per_sec_fleet`` (vs_baseline = fleet
    / in-process router — the socket+pickle tax on shared cores; the
    disaggregation win needs separate hosts) and
    ``serve_goodput_fleet_kill``: completed-request fraction with a
    decode worker SIGKILLed mid-trace — the router replays the dead
    worker's requests from its journal on the survivor (vs_baseline =
    fleet / single engine chaos-killed with restart budget 0, the
    same outage the replicated cell baselines against)."""
    import shutil
    import tempfile

    import jax

    if jax.default_backend() != "cpu":
        emit("serve_tokens_per_sec_fleet", 0.0, "tokens/sec",
             skipped="fleet cell is CPU-host only (worker processes "
                     "cannot share one accelerator)")
        return
    from cxxnet_tpu.serve import (EngineFailedError, FleetRouter,
                                  InferenceServer, QueueFullError)

    c, cfg, params = _repl_model()
    trace = _repl_trace(c)
    kw = dict(slots=c["slots"], queue=c["n_requests"],
              prefill_chunk=c["chunk"])
    wenv = {"JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1"}
    aot = tempfile.mkdtemp(prefix="cxn-fleet-bench-aot")
    try:
        wall_r, mr = run_serve_trace(cfg, params, trace, replicas=2,
                                     **kw)
        tps_r = mr["tokens_generated"] / wall_r

        def fleet_pass(r):
            # warm pass fills every worker's caches and compiles (or
            # AOT-loads) every program; the timed pass is steady state
            for h in [r.submit(p, max_tokens=m) for _, p, m in trace]:
                r.result(h, timeout=600)
            t0 = time.perf_counter()
            handles = []
            for gap, p, m in trace:             # open loop
                time.sleep(gap)
                handles.append(r.submit(p, max_tokens=m))
            toks = 0
            for (_, p, m), h in zip(trace, handles):
                res = r.result(h, timeout=600)
                if res.status == "ok":          # tokens = full seq
                    toks += len(res.tokens) - len(p)
            return time.perf_counter() - t0, toks

        with FleetRouter(cfg, params, prefill=1, decode=2,
                         worker_env=wenv, aot_cache=aot, **kw) as r:
            wall_f, toks_f = fleet_pass(r)
            mig = r.metrics()["fleet"]
        tps_f = toks_f / wall_f
        emit("serve_tokens_per_sec_fleet", tps_f, "tokens/sec",
             tps_f / max(tps_r, 1e-9),
             router_tokens_per_sec=round(tps_r, 1),
             migrations=mig["migrations"],
             kv_wire_bytes=mig["kv_wire_bytes"])

        # availability: SIGKILL a decode worker after ~40% of the
        # trace is in; the journal replays its requests on the
        # survivor while a replacement respawns
        def goodput_single():
            srv = InferenceServer(cfg, params, chaos="tick_raise@40",
                                  max_restarts=0, **kw)
            ok, handles = 0, []
            try:
                for gap, p, m in trace:
                    time.sleep(gap)
                    try:
                        handles.append(srv.submit(p, max_tokens=m))
                    except (EngineFailedError, QueueFullError):
                        pass
                for h in handles:
                    if srv.result(h, timeout=600).status == "ok":
                        ok += 1
            finally:
                srv.shutdown(drain=False)
            return ok / float(len(trace))

        g_single = goodput_single()
        ok = 0
        with FleetRouter(cfg, params, prefill=1, decode=2,
                         worker_env=wenv, aot_cache=aot,
                         heartbeat_s=0.5, **kw) as r:
            handles = []
            for gap, p, m in trace:
                time.sleep(gap)
                handles.append(r.submit(p, max_tokens=m))
            # kill once ~40% of the results are in: the victim is
            # mid-decode on live streams, not idling through the
            # submission burst
            killed = False
            for i, h in enumerate(handles):
                if r.result(h, timeout=600).status == "ok":
                    ok += 1
                if not killed and i >= int(0.4 * len(handles)):
                    victims = r._live("decode")
                    if victims:
                        victims[0].proc.kill()
                    killed = True
            mk = r.metrics()["fleet"]
        g_fleet = ok / float(len(trace))
        emit("serve_goodput_fleet_kill", g_fleet, "fraction",
             g_fleet / max(g_single, 1e-9),
             single_goodput=round(g_single, 3),
             replays=mk["replays"], restarts=mk["restarts"])
    finally:
        shutil.rmtree(aot, ignore_errors=True)


def bench_serve_tenanted():
    """Multi-tenant SLO cell (doc/serving.md "Multi-tenant SLOs"): a
    3x-overload Poisson trace with a guaranteed / standard /
    best_effort tenant mix (1/4 : 1/4 : 1/2) served by a tenanted
    server — guaranteed submits block at the door (an SLO client waits,
    never drops) and carries no deadline; standard and best-effort
    carry tenant-default deadlines (tight for best-effort), so rung-3
    shedding lands on the best-effort class first. Emits
    ``serve_goodput_guaranteed_overload`` (guaranteed completion
    fraction; the acceptance gate is 1.0 — vs_baseline IS the value)
    and ``serve_p95_ttft_ms_guaranteed_overload`` (the guaranteed
    tenant's p95 TTFT under overload; vs_baseline = the SAME trace
    through an UNTENANTED server's global FIFO / global ladder — > 1
    means tenancy bought the paying tenant latency isolation).
    Best-effort sheds ride along as fields, with the minimum observed
    finite ``retry_after_ms`` hint."""
    import time as _time

    from cxxnet_tpu.serve import InferenceServer, QueueFullError

    c, cfg, params = _repl_model()
    rs = np.random.RandomState(c["seed"] + 31)
    n = 36
    tenants = rs.choice(["gold", "std", "free"], n, p=[0.25, 0.25, 0.5])
    lens = rs.choice([8, 16], n)
    maxt = rs.choice(list(c["max_new"]), n)
    prompts = [rs.randint(0, c["vocab"], (int(l),)).astype(np.int32)
               for l in lens]
    kw = dict(slots=c["slots"], queue=12, prefill_chunk=c["chunk"])

    # calibration: closed-loop service rate of this trace on this rig,
    # warmed — the denominator that makes "3x overload" honest
    srv = InferenceServer(cfg, params, **kw)
    try:
        for _ in range(2):
            t0 = _time.perf_counter()
            hs = [srv.submit(p, max_tokens=int(m))
                  for p, m in zip(prompts[:12], maxt[:12])]
            for h in hs:
                srv.result(h)
            cal_wall = _time.perf_counter() - t0
    finally:
        srv.shutdown()
    rate = 12.0 / cal_wall                  # requests/sec at capacity
    gaps = rs.exponential(1.0 / (3.0 * rate), n)
    # deadlines via tenant defaults: best_effort gets ~2 service
    # times, standard ~8 — the shed pressure lands inverse-priority
    svc_ms = 1e3 / rate * c["slots"]
    spec = ("gold:prio=G;std:prio=S,timeout_ms=%.0f;"
            "free:prio=B,timeout_ms=%.0f" % (8 * svc_ms, 2 * svc_ms))

    def run(tenanted):
        srv = InferenceServer(
            cfg, params, tenants=spec if tenanted else "", **kw)
        out = {"gold_ttft": [], "gold_ok": 0, "shed": 0, "retry": []}
        try:
            handles = []
            for gap, t, p, m in zip(gaps, tenants, prompts, maxt):
                _time.sleep(float(gap))
                try:
                    handles.append((t, srv.submit(
                        p, max_tokens=int(m), tenant=str(t),
                        block=(t == "gold"))))
                except QueueFullError as e:
                    if e.retry_after_ms > 0:
                        out["retry"].append(e.retry_after_ms)
                    out["shed"] += 1
            for t, h in handles:
                res = srv.result(h, timeout=600)
                if t == "gold" and res.status == "ok":
                    out["gold_ok"] += 1
                    out["gold_ttft"].append(res.ttft_ms)
                elif res.status == "shed":
                    out["shed"] += 1
                    if res.retry_after_ms > 0:
                        out["retry"].append(res.retry_after_ms)
        finally:
            srv.shutdown()
        return out

    mt = run(tenanted=True)
    mu = run(tenanted=False)
    gold_total = int(sum(1 for t in tenants if t == "gold"))
    g = mt["gold_ok"] / float(max(1, gold_total))
    p95_t = float(np.percentile(mt["gold_ttft"], 95)) \
        if mt["gold_ttft"] else 0.0
    p95_u = float(np.percentile(mu["gold_ttft"], 95)) \
        if mu["gold_ttft"] else 0.0
    emit("serve_goodput_guaranteed_overload", g, "fraction", g,
         be_shed=mt["shed"],
         min_retry_after_ms=(round(min(mt["retry"]), 1)
                             if mt["retry"] else None),
         overload_factor=3.0)
    emit("serve_p95_ttft_ms_guaranteed_overload", p95_t, "ms",
         p95_u / max(p95_t, 1e-9),
         untenanted_p95_ms=round(p95_u, 1))


def serve_spec_trace(cfg, params, cell=None):
    """Seeded repetitive-suffix serving trace: [(gap_s, prompt,
    max_tokens)] with Poisson open-loop arrivals — every prompt is a
    window cut from the model's OWN greedy stream (self-similar
    traffic, the shape where the n-gram drafter's prompt lookup hits on
    any checkpoint; see SPEC_CELL)."""
    import jax
    from cxxnet_tpu.models.gpt import gpt_decode

    c = cell or SERVE_CELL
    rs = np.random.RandomState(c["seed"] + 17)
    seed = jax.numpy.asarray(
        rs.randint(0, c["vocab"], (1, 8)).astype(np.int32))
    # window + warm-stream lengths scale with the cell's seq_len so the
    # trace stays valid for CPU-scaled geometries too
    win = min(64, cfg.seq_len // 3)
    warm_n = min(160, cfg.seq_len - 9)
    warm = np.asarray(gpt_decode(params, seed, warm_n, cfg))[0]
    gaps = rs.exponential(c["mean_gap_ms"] / 1e3, c["n_requests"])
    maxt = rs.choice([32, 64], c["n_requests"])
    out = []
    for g, m in zip(gaps, maxt):
        start = int(rs.randint(8, len(warm) - win))
        out.append((float(g), warm[start:start + win].astype(np.int32),
                    int(m)))
    return out


def bench_serve_spec():
    """Speculative serving cell (round 10): the SERVE_CELL model served
    with the n-gram drafter (spec_mode=ngram) vs the PR-4 serving
    configuration (chunked prefill + prefix cache, no speculation) on
    the SAME repetitive-suffix request set. The HEADLINE is the
    low-occupancy single-slot pass — the latency regime speculation is
    for, where a verify forward has the offline path's economics (it
    replaces batch-1 ticks one-for-one) — with vs_baseline =
    spec/non-spec tokens/s. The saturated 8-slot open-loop pass rides
    along as extra fields: there per-slot verifies compete with the
    batched tick, and the scheduler's accept-rate back-off
    (serve/scheduler.py SPEC_BACKOFF_*) is what bounds the loss —
    batched_vs_baseline ~1.0 with backoffs > 0 means the containment
    worked, not that speculation won."""
    import jax
    from cxxnet_tpu.models.gpt import GPTConfig, gpt_init

    c = SERVE_CELL
    cfg = GPTConfig(vocab_size=c["vocab"], seq_len=c["seq"],
                    n_layer=c["layers"], n_head=c["heads"], feat=c["feat"],
                    n_microbatch=1, dtype="bfloat16")
    params = gpt_init(jax.random.PRNGKey(0), cfg)
    trace = serve_spec_trace(cfg, params, c)
    # headline: sequential single-slot service (no arrival gaps)
    t1 = [(0.0, p, m) for _, p, m in trace[:c["n_requests"] // 2]]
    kw1 = dict(slots=1, queue=c["n_requests"])
    wall, m_ = run_serve_trace(cfg, params, t1, spec_mode="ngram",
                               spec_len=8, **kw1)
    wall0, m0 = run_serve_trace(cfg, params, t1, **kw1)
    tps = m_["tokens_generated"] / wall
    tps0 = m0["tokens_generated"] / wall0
    # rider: the saturated 8-slot open-loop pass
    kw8 = dict(slots=c["slots"], queue=c["n_requests"])
    wall8, m8 = run_serve_trace(cfg, params, trace, spec_mode="ngram",
                                spec_len=8, **kw8)
    wall80, m80 = run_serve_trace(cfg, params, trace, **kw8)
    tps8 = m8["tokens_generated"] / wall8
    tps80 = m80["tokens_generated"] / wall80
    emit("serve_spec_tokens_per_sec", tps, "tokens/sec", tps / tps0,
         accept_rate=round(m_["accept_rate"], 3),
         spec_tokens_per_forward=round(m_["spec_tokens_per_forward"], 2),
         spec_rollback_rate=round(m_["spec_rollback_rate"], 3),
         nonspec_tokens_per_sec=round(tps0, 1),
         batched_vs_baseline=round(tps8 / tps80, 3),
         batched_accept_rate=round(m8["accept_rate"], 3),
         batched_backoffs=m8["spec_backoffs"])


def bench_obs_overhead(cell=None):
    """Span-tracing cost gate (round 11, doc/observability.md): the
    SERVE_CELL open-loop trace served with the obs tracer ON (the
    shipped default — every request records its span tree, the
    registry's callback metrics are live either way) vs a disabled
    tracer, emitting the throughput overhead percentage. The obs cost
    budget is <= 2%: tracing is designed to stay on under production
    traffic (monotonic-clock spans, one lock-guarded deque append per
    span, NO per-token records in the tick loop), and this line is what
    enforces that claim release over release. Best-of-3 per arm with
    the arms interleaved, so platform drift lands on both and the
    percentage compares each arm's best achievable rate (a mean would
    charge tracing for scheduler jitter)."""
    import jax
    from cxxnet_tpu.models.gpt import GPTConfig, gpt_init
    from cxxnet_tpu.obs.devprof import DEFAULT_PROF_EVERY
    from cxxnet_tpu.obs.trace import Tracer

    c = cell or SERVE_CELL
    cfg = GPTConfig(vocab_size=c["vocab"], seq_len=c["seq"],
                    n_layer=c["layers"], n_head=c["heads"], feat=c["feat"],
                    n_microbatch=1, dtype="bfloat16")
    params = gpt_init(jax.random.PRNGKey(0), cfg)
    trace = serve_trace(c)
    # prof_every at the CLI serving default in BOTH arms: the gate
    # certifies the shipped telemetry configuration — span tracing on
    # top of live device-time sampling — not a stripped-down one
    kw = dict(slots=c["slots"], queue=c["n_requests"],
              prof_every=DEFAULT_PROF_EVERY)
    best = {"on": 0.0, "off": 0.0}
    for _ in range(3):
        for arm in ("on", "off"):
            wall, m_ = run_serve_trace(cfg, params, trace,
                                       tracer=Tracer(enabled=arm == "on"),
                                       **kw)
            best[arm] = max(best[arm], m_["tokens_generated"] / wall)
    pct = 100.0 * (best["off"] - best["on"]) / best["off"]
    emit("obs_overhead_pct", pct, "%",
         tracing_on_tokens_per_sec=round(best["on"], 1),
         tracing_off_tokens_per_sec=round(best["off"], 1))


def bench_lint():
    """cxn-lint pass-1 wall time on the LARGEST example config (round 8):
    the linter runs at every CXN_LINT startup and in CI, so its cost is a
    perf surface like any other — this line keeps it visible in the
    trajectory. Warm pass timed (the registry's AST introspection caches
    amortize across configs in a CI run; the first pass pays them)."""
    import glob
    from cxxnet_tpu.analysis import lint_config_file
    path = max(glob.glob(os.path.join(os.path.dirname(__file__), "example",
                                      "*", "*.conf")), key=os.path.getsize)
    result = lint_config_file(path)          # cold: fills registry caches
    assert result.ok(), "largest example %s must lint clean" % path
    t0 = time.perf_counter()
    lint_config_file(path)
    ms = (time.perf_counter() - t0) * 1e3
    emit("lint_wall_ms", ms, "ms", config=os.path.relpath(
        path, os.path.dirname(__file__)))
    # pass 3 (the CXN3xx concurrency lint) walks every package source
    # file per run — a pure-AST cost, but one tier-1 CI now pays on
    # every gate, so it gets its own trajectory line
    from cxxnet_tpu.analysis import lint_threads
    from cxxnet_tpu.analysis.findings import LintReport
    rep = LintReport()
    lint_threads(report=rep)                 # cold: bytecode/AST warmup
    assert rep.ok(), "package must pass the concurrency lint"
    t0 = time.perf_counter()
    lint_threads(report=LintReport())
    ms = (time.perf_counter() - t0) * 1e3
    emit("lint_threads_wall_ms", ms, "ms")


def bench_serve_cold_start():
    """AOT executable cache cold-start cell (round 18,
    doc/performance.md "AOT executable cache"): the flagship serve
    geometry built from scratch with the in-process compiled-program
    caches cleared before each arm — a fresh-process stand-in (jax's
    glue-op caches stay warm in BOTH arms, so the delta isolates the
    serve programs, which dominate startup).

    * ``engine_cold_start_ms``: InferenceServer() construction ->
      first probe token, warm AOT cache arm; vs_baseline = the no-cache
      arm / warm arm (>1 = the cache wins cold start).
    * ``engine_recovery_ms``: the same two arms through PR 9's actual
      recovery path — a chaos-killed tick mid-request forces
      ``_do_recover`` (teardown + rebuild + replay), with the program
      caches cleared after build so the rebuild must RE-ACQUIRE every
      program: from disk (warm arm) or by recompiling at the next
      fetch (no-cache arm). Reported value = submit -> replayed-ok
      wall of the faulted request.
    """
    import tempfile

    import jax
    from cxxnet_tpu.models.gpt import GPTConfig, gpt_init
    from cxxnet_tpu.serve import InferenceServer
    from cxxnet_tpu.serve.engine import clear_program_caches

    c = SERVE_CELL
    cfg = GPTConfig(vocab_size=c["vocab"], seq_len=c["seq"],
                    n_layer=c["layers"], n_head=c["heads"], feat=c["feat"],
                    n_microbatch=1, dtype="bfloat16")
    params = gpt_init(jax.random.PRNGKey(0), cfg)
    rs = np.random.RandomState(1)
    probe = rs.randint(0, c["vocab"], (17,)).astype(np.int32)
    # aot_cache="" falls back to CXN_AOT_CACHE — a rig that exports it
    # would silently warm the no-cache baseline arms; isolate the cell
    env_cache = os.environ.pop("CXN_AOT_CACHE", None)

    def cold_start(aot_dir):
        clear_program_caches()
        t0 = time.perf_counter()
        srv = InferenceServer(cfg, params, slots=4, queue=8,
                              aot_cache=aot_dir)
        res = srv.result(srv.submit(probe, max_tokens=2), timeout=600)
        ms = (time.perf_counter() - t0) * 1e3
        assert res.status == "ok", res.status
        return srv, ms

    def recovery(aot_dir):
        clear_program_caches()
        srv = InferenceServer(cfg, params, slots=4, queue=8,
                              aot_cache=aot_dir, chaos="tick_raise@4",
                              max_restarts=2)
        # drop the build-time programs: the recovery rebuild (and the
        # no-cache arm's next tick) must re-acquire every executable,
        # exactly like a supervisor-restarted fresh process
        clear_program_caches()
        t0 = time.perf_counter()
        res = srv.result(srv.submit(probe, max_tokens=8), timeout=600)
        ms = (time.perf_counter() - t0) * 1e3
        m = srv.metrics()
        srv.shutdown(drain=False)
        assert res.status == "ok", res.status
        assert m["resilience"]["restarts"] >= 1, "fault did not fire"
        return ms, m["resilience"]["last_recover_ms"]

    try:
        with tempfile.TemporaryDirectory() as d:
            srv, _ = cold_start(d)          # populate the cache
            srv.shutdown(drain=False)
            srv, ms_nocache = cold_start("")
            srv.shutdown(drain=False)
            srv, ms_warm = cold_start(d)
            hits = srv.metrics()["aot_cache"]["hits"]
            srv.shutdown(drain=False)
            assert hits >= 2, "warm arm must load from the cache"
            emit("engine_cold_start_ms", ms_warm, "ms",
                 ms_nocache / ms_warm, nocache_ms=round(ms_nocache, 1))
            rec_nocache, _ = recovery("")
            rec_warm, rebuild_ms = recovery(d)
            emit("engine_recovery_ms", rec_warm, "ms",
                 rec_nocache / rec_warm, nocache_ms=round(rec_nocache, 1),
                 rebuild_ms=round(rebuild_ms, 1))
    finally:
        if env_cache is not None:
            os.environ["CXN_AOT_CACHE"] = env_cache


def main() -> int:
    rc = 0
    for fn in (bench_alexnet, bench_resnet50, bench_feed_overlap, bench_gpt,
               bench_moe, bench_decode, bench_decode_spec, bench_serve,
               bench_serve_prefill_heavy, bench_serve_paged,
               bench_serve_fused, bench_serve_longctx,
               bench_serve_autotune, bench_serve_int8, bench_serve_int4,
               bench_serve_lora, bench_serve_sharded,
               bench_serve_replicated, bench_serve_fleet,
               bench_serve_tenanted,
               bench_serve_spec, bench_serve_cold_start,
               bench_obs_overhead, bench_lint):
        try:
            fn()
        except Exception as e:                      # noqa: BLE001
            print("%s failed: %r" % (fn.__name__, e), file=sys.stderr)
            rc = 1
        gc.collect()                # drop device buffers between benchmarks
    return rc


if __name__ == "__main__":
    sys.exit(main())
