"""Train a character-level GPT with the 4D-parallel flagship stack.

Self-contained: builds a byte-level corpus from this file's own source (or
any file passed via --text), trains a small GPT over a configurable device
mesh, and samples from the model at the end.

Runs anywhere:
  # one device (TPU chip or CPU)
  python example/GPT/train_gpt.py --steps 200

  # 8 virtual CPU devices: dp2 x pp2 x sp... pick any factorization
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python example/GPT/train_gpt.py --pp 2 --tp 2 --steps 100

The mesh axes multiply: devices = dp * pp * sp * tp (dp absorbs the rest).
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

# the fused whole-step decode kernel keeps a layer's weights + caches
# resident in VMEM (ops/pallas_kernels.fused_decode_supported gates on
# this being configured); also +4% on the conv zoo, neutral on GPT train
os.environ.setdefault("LIBTPU_INIT_ARGS",
                      "--xla_tpu_scoped_vmem_limit_kib=65536")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--text", default=__file__,
                    help="corpus file (byte-level; default: this script)")
    ap.add_argument("--steps", type=int, default=200)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--feat", type=int, default=128)
    ap.add_argument("--pp", type=int, default=1, help="pipeline stages")
    ap.add_argument("--sp", type=int, default=1, help="sequence shards")
    ap.add_argument("--tp", type=int, default=1, help="tensor shards")
    ap.add_argument("--microbatch", type=int, default=2)
    ap.add_argument("--eta", type=float, default=None,
                    help="learning rate (default: 0.1 for sgd, 0.003 for "
                         "--adam)")
    ap.add_argument("--bf16", action="store_true")
    ap.add_argument("--adam", action="store_true",
                    help="Adam instead of momentum SGD")
    ap.add_argument("--remat", action="store_true",
                    help="rematerialize blocks in backward (less HBM)")
    ap.add_argument("--remat-mode", default="block",
                    choices=["block", "attn_saved"],
                    help="remat boundary (attn_saved wins at d>=128 scale)")
    ap.add_argument("--attn-layout", default="auto",
                    choices=["auto", "bnhd", "bhnd"],
                    help="kernel-boundary layout (auto: head-major when "
                         "head_dim >= 128; composes with both --sp modes)")
    ap.add_argument("--sp-mode", default="ring",
                    choices=["ring", "ulysses"],
                    help="sequence-parallel attention variant")
    ap.add_argument("--zero", type=int, default=0, choices=[0, 1, 3],
                    help="ZeRO level: 1 shards optimizer state over data, "
                         "3 also shards params (FSDP)")
    ap.add_argument("--ckpt", default="",
                    help="checkpoint dir: resume from it if present, save "
                         "into it at the end (sharded orbax format; works "
                         "across different mesh layouts)")
    args = ap.parse_args()

    import jax
    import numpy as np

    from cxxnet_tpu.models.gpt import (GPTConfig, gpt_decode, gpt_init,
                                       gpt_opt_init, gpt_place,
                                       make_train_step)
    from cxxnet_tpu.parallel.mesh import make_mesh

    raw = np.frombuffer(open(args.text, "rb").read(), np.uint8)
    vocab = 256
    print("corpus: %s (%d bytes)" % (args.text, raw.size))

    cfg = GPTConfig(vocab_size=vocab, seq_len=args.seq, n_layer=args.layers,
                    n_head=args.heads, feat=args.feat,
                    n_microbatch=args.microbatch,
                    dtype="bfloat16" if args.bf16 else "float32",
                    remat=args.remat, remat_mode=args.remat_mode,
                    attn_layout=args.attn_layout,
                    seq_parallel_mode=args.sp_mode)
    optname = "adam" if args.adam else "sgd"
    if args.eta is None:
        args.eta = 0.003 if args.adam else 0.1

    mesh = make_mesh(devices=jax.devices(), pipeline_parallel=args.pp,
                     seq_parallel=args.sp, model_parallel=args.tp)
    print("mesh:", dict(zip(mesh.axis_names, mesh.devices.shape)))

    params = gpt_place(gpt_init(jax.random.PRNGKey(0), cfg), mesh,
                       zero=args.zero)
    opt = gpt_opt_init(params, mesh, optname, zero=args.zero)
    if args.ckpt and os.path.isdir(args.ckpt):
        from cxxnet_tpu.utils import checkpoint
        try:
            state = checkpoint.restore(args.ckpt,
                                       like={"params": params, "opt": opt})
        except Exception as e:
            raise SystemExit(
                "cannot resume from %s:\n  %s\n"
                "(if the stored tree structure differs, common causes are a "
                "different --layers/--feat/--tp than the checkpoint was "
                "written with, or an optimizer mismatch: --%s here vs the "
                "checkpoint's; checkpoints from before the --adam flag "
                "stored the key 'mom')" % (args.ckpt, e, optname)) from e
        params, opt = state["params"], state["opt"]
        print("resumed from %s" % args.ckpt)
    step = make_train_step(cfg, mesh, eta=args.eta, optimizer=optname,
                           zero=args.zero)

    rs = np.random.RandomState(0)
    n_tok = args.batch * args.seq

    def sample_batch():
        starts = rs.randint(0, raw.size - args.seq - 1, args.batch)
        return jax.numpy.asarray(
            np.stack([raw[s:s + args.seq] for s in starts]).astype(np.int32))

    t0 = time.perf_counter()
    for i in range(args.steps):
        params, opt, loss = step(params, opt, sample_batch())
        if i % 20 == 0 or i == args.steps - 1:
            dt = time.perf_counter() - t0
            tps = n_tok * (i + 1) / dt
            print("step %4d  loss %.3f  (%.0f tok/s)" % (i, float(loss), tps))

    if args.ckpt:
        from cxxnet_tpu.utils import checkpoint
        checkpoint.save(args.ckpt, {"params": params, "opt": opt})
        print("checkpoint saved to %s" % args.ckpt)

    # greedy generation with the KV-cache decoder (one forward per token;
    # batch padded to the training batch for sharding divisibility)
    prompt = np.tile(raw[:32].astype(np.int32), (args.batch, 1))
    max_new = min(args.seq - 32, 96)
    out = gpt_decode(params, jax.numpy.asarray(prompt), max_new, cfg, mesh)
    txt = bytes(np.asarray(out)[0].astype(np.uint8)).decode("utf-8",
                                                            "replace")
    print("--- greedy sample ---")
    print(txt)
    return 0


if __name__ == "__main__":
    sys.exit(main())
