#!/bin/sh
# Split a shuffled list into tr.lst (96%) and va.lst (4%).
[ -n "$1" ] || { echo "usage: $0 train.lst"; exit 1; }
n=$(wc -l < "$1")
nva=$((n / 25))
head -n "$nva" "$1" > va.lst
tail -n +"$((nva + 1))" "$1" > tr.lst
echo "split $n -> $(wc -l < tr.lst) train / $(wc -l < va.lst) val"
