"""Assemble the Kaggle submission CSV from extracted probabilities.

Usage:
    python make_submission.py sampleSubmission.csv test.lst test.txt out.csv

`test.txt` is the output of pred.conf (one row of 121 softmax values per
test instance, in test.lst order); the sample submission supplies the
header and the expected image-name column.
"""

import csv
import os
import sys


def main(argv):
    if len(argv) != 5:
        sys.stderr.write(__doc__)
        return 1
    sample_csv, lst_path, prob_path, out = argv[1:]
    with open(sample_csv) as f:
        header = next(csv.reader(f))
    names = []
    with open(lst_path) as f:
        for line in f:
            parts = line.rstrip("\n").split("\t")
            names.append(os.path.basename(parts[2]))
    with open(prob_path) as f, open(out, "w", newline="") as fo:
        w = csv.writer(fo)
        w.writerow(header)
        for i, line in enumerate(f):
            probs = line.split()
            if len(probs) != len(header) - 1:
                raise SystemExit(
                    "row %d has %d probabilities, expected %d"
                    % (i, len(probs), len(header) - 1))
            w.writerow([names[i]] + probs)
    print("wrote %s (%d rows)" % (out, len(names)))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
