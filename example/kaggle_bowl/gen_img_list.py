"""Generate an image list (`index \t label \t path`) for the bowl dataset.

Usage:
    python gen_img_list.py train <sampleSubmission.csv> <img_root> train.lst
    python gen_img_list.py test  <sampleSubmission.csv> <img_root> test.lst

The class order (label index 0..120) is the column order of the sample
submission header, so probabilities extracted with pred.conf line up with
the submission columns. Train mode expects <img_root>/<class_name>/*.jpg;
test mode lists <img_root>/*.jpg with label 0.
"""

import csv
import os
import random
import sys


def class_order(sample_csv):
    with open(sample_csv) as f:
        header = next(csv.reader(f))
    return header[1:]          # first column is "image"


def main(argv):
    if len(argv) != 5:
        sys.stderr.write(__doc__)
        return 1
    mode, sample_csv, root, out = argv[1:]
    classes = class_order(sample_csv)
    rows = []
    if mode == "train":
        for li, cname in enumerate(classes):
            d = os.path.join(root, cname)
            if not os.path.isdir(d):
                continue
            for fname in sorted(os.listdir(d)):
                rows.append((li, os.path.join(d, fname)))
        random.seed(888)
        random.shuffle(rows)
    elif mode == "test":
        for fname in sorted(os.listdir(root)):
            rows.append((0, os.path.join(root, fname)))
    else:
        raise SystemExit("mode must be train or test")
    with open(out, "w") as fo:
        for i, (label, path) in enumerate(rows):
            fo.write("%d\t%d\t%s\n" % (i, label, path))
    print("wrote %d entries to %s (%d classes)" % (len(rows), out,
                                                   len(classes)))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
