"""Train the MNIST MLP through the Python wrapper API.

The wrapper (`cxxnet_tpu.wrapper`) mirrors the reference's Python module
(wrapper/cxxnet.py): config-string iterators, Net, and a train() loop —
this script is the reference example/MNIST/mnist.py workflow on the TPU
framework. Fetch the idx.gz files first (see run.sh), then:

    python example/MNIST/mnist.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from cxxnet_tpu import wrapper as cxxnet  # noqa: E402

data = cxxnet.DataIter("""
iter = mnist
    path_img = "./data/train-images-idx3-ubyte.gz"
    path_label = "./data/train-labels-idx1-ubyte.gz"
    shuffle = 1
iter = end
input_shape = 1,1,784
batch_size = 100
""")
print("init data iter")

deval = cxxnet.DataIter("""
iter = mnist
    path_img = "./data/t10k-images-idx3-ubyte.gz"
    path_label = "./data/t10k-labels-idx1-ubyte.gz"
iter = end
input_shape = 1,1,784
batch_size = 100
""")
print("init eval iter")

cfg = """
netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 100
  init_sigma = 0.01
layer[+1:sg1] = sigmoid:se1
layer[sg1->fc2] = fullc:fc2
  nhidden = 10
  init_sigma = 0.01
layer[+0] = softmax
netconfig=end

input_shape = 1,1,784
batch_size = 100
"""

param = {
    "eta": 0.1,
    "momentum": 0.9,
    "wd": 0.0,
    "metric": "error",
}

net = cxxnet.train(cfg, data, 15, param, eval_data=deval)

# weights are numpy in / numpy out, as in the reference wrapper
w = net.get_weight("fc1", "wmat")
print("fc1 weight shape:", w.shape)
