"""Write REAL handwritten-digit data in the mnist iterator's idx.gz format.

This sandbox has no network egress, so `run.sh`'s MNIST download cannot
run here. For committed, reproducible real-data convergence evidence the
framework's repo uses the UCI Optical Recognition of Handwritten Digits
set (1,797 real scanned digits, 8x8 grayscale, bundled with scikit-learn
as `load_digits`) written into the exact on-disk format the `mnist`
iterator consumes (idx3/idx1, gzip — iter_mnist-inl.hpp:14-158 parity).
`MNIST.conf` / `MNIST_CONV.conf` remain the full-size recipes when the
download is possible.

Usage: python example/MNIST/digits_data.py [outdir=./data-digits]
"""

import gzip
import os
import struct
import sys

import numpy as np


def write_idx(outdir: str, seed: int = 7, n_test: int = 297) -> None:
    from sklearn.datasets import load_digits
    d = load_digits()
    # 0..16 pixel range -> 0..255 uint8 (the iterator divides by 256)
    imgs = np.clip(d.images * 16, 0, 255).astype(np.uint8)
    labels = d.target.astype(np.uint8)
    order = np.random.RandomState(seed).permutation(imgs.shape[0])
    imgs, labels = imgs[order], labels[order]
    n_train = imgs.shape[0] - n_test
    os.makedirs(outdir, exist_ok=True)
    splits = {
        "train-images-idx3-ubyte.gz": imgs[:n_train],
        "t10k-images-idx3-ubyte.gz": imgs[n_train:],
    }
    for name, arr in splits.items():
        with gzip.open(os.path.join(outdir, name), "wb") as f:
            n, r, c = arr.shape
            f.write(struct.pack(">iiii", 2051, n, r, c))
            f.write(arr.tobytes())
    for name, arr in (("train-labels-idx1-ubyte.gz", labels[:n_train]),
                      ("t10k-labels-idx1-ubyte.gz", labels[n_train:])):
        with gzip.open(os.path.join(outdir, name), "wb") as f:
            f.write(struct.pack(">ii", 2049, arr.shape[0]))
            f.write(arr.tobytes())
    print("wrote %d train / %d test real digit images to %s"
          % (n_train, n_test, outdir))


if __name__ == "__main__":
    write_idx(sys.argv[1] if len(sys.argv) > 1 else "./data-digits")
