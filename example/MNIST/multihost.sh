#!/bin/bash
# Multi-host training demo — the analogue of the reference's mpi.conf
# (/root/reference/example/MNIST/mpi.conf: num_servers/num_workers + ps-lite
# launcher). Here there are no parameter servers: each process joins one
# global device mesh via jax.distributed (CXXNET_* env vars, read by
# cxxnet_tpu.parallel.distributed.init_distributed) and gradients meet in
# XLA collectives. Each process feeds its own shard of every global batch.
#
# This demo runs 2 processes on localhost with 2 virtual CPU devices each
# (a 4-device global mesh) — on real TPU pods, run one process per host
# with no XLA_FLAGS/JAX_PLATFORMS overrides and point CXXNET_COORDINATOR
# at host 0.
#
#   ./multihost.sh MNIST.conf
set -e
CONF="${1:-MNIST.conf}"
PORT="${PORT:-9876}"

run_rank() {
  JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=2 \
  CXXNET_COORDINATOR="127.0.0.1:${PORT}" \
  CXXNET_NUM_WORKER=2 \
  CXXNET_RANK="$1" \
  python -m cxxnet_tpu "${CONF}" "${@:2}"
}

trap 'kill $PID0 $PID1 2>/dev/null || true' EXIT INT TERM
run_rank 0 "$@" &
PID0=$!
run_rank 1 "$@" > /dev/null 2>&1 &
PID1=$!
wait $PID0
wait $PID1
trap - EXIT
echo "multihost run finished"
