#!/bin/bash
# Fetch MNIST and train the MLP config end-to-end.
set -e
cd "$(dirname "$0")"

mkdir -p data models
for f in train-images-idx3-ubyte.gz train-labels-idx1-ubyte.gz \
         t10k-images-idx3-ubyte.gz t10k-labels-idx1-ubyte.gz; do
    [ -f "data/$f" ] || wget -O "data/$f" \
        "https://ossci-datasets.s3.amazonaws.com/mnist/$f"
done

python -m cxxnet_tpu MNIST.conf "$@"
