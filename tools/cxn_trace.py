#!/usr/bin/env python
"""cxn-trace: offline tooling for obs span dumps (doc/observability.md).

Subcommands:

  export  <spans.jsonl> [-o out.trace.json]
      Convert a raw span dump (``Tracer.dump_jsonl`` /
      ``obs_export``'s ``<prefix>.spans.jsonl``) into Chrome-trace
      JSON, loadable in Perfetto (https://ui.perfetto.dev) or
      chrome://tracing. Already-converted Chrome JSON passes through
      unchanged, so the command is idempotent.

  summary <spans.jsonl | trace.json> [--top N]
      Human triage without a trace viewer: the top-N slowest requests
      (by the ``request`` root span) and a per-phase time breakdown
      (count / total / mean / max per span name) from either file
      format.

The serve loop writes these files when ``obs_export = <prefix>`` is
set; ``wrapper.Net.trace_export()`` produces the Chrome form directly.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from cxxnet_tpu.obs.trace import (REQ_TID_BASE,    # noqa: E402
                                  spans_to_chrome)


def load_spans(path: str):
    """Either input format -> (spans, other_data): a flat span list of
    {name, cat, ts, dur, tid, args} with ts/dur in SECONDS, plus the
    source's ``otherData`` metadata (epoch, dropped-span count, slow
    reason — empty for JSONL input, which carries none) so a re-export
    can carry it through instead of erasing it."""
    with open(path) as f:
        text = f.read()
    # sniff: a Chrome trace is ONE JSON document with traceEvents; a
    # span dump is one JSON object PER LINE (whole-text parse fails on
    # the second line)
    doc = None
    try:
        parsed = json.loads(text)
        if isinstance(parsed, dict) and "traceEvents" in parsed:
            doc = parsed
    except json.JSONDecodeError:
        pass
    if doc is not None:
        spans = []
        for ev in doc.get("traceEvents", []):
            if ev.get("ph") != "X":
                continue
            spans.append({"name": ev["name"], "cat": ev.get("cat", ""),
                          "ts": ev["ts"] / 1e6, "dur": ev["dur"] / 1e6,
                          "tid": ev.get("tid", 0),
                          "args": ev.get("args", {})})
        other = {k: v for k, v in doc.get("otherData", {}).items()
                 if k != "format"}       # spans_to_chrome re-stamps it
        return spans, other
    spans = []
    for i, line in enumerate(text.splitlines()):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError as e:
            raise SystemExit("%s:%d: not a span JSONL line (%s)"
                             % (path, i + 1, e))
        for k in ("name", "ts", "dur", "tid"):
            if k not in rec:
                raise SystemExit("%s:%d: span line missing %r"
                                 % (path, i + 1, k))
        rec.setdefault("cat", "")
        rec.setdefault("args", {})
        spans.append(rec)
    return spans, {}


def _default_out(path: str) -> str:
    """<base>.trace.json with the known suffixes stripped first, so
    exporting run.spans.jsonl gives run.trace.json and re-exporting
    run.trace.json overwrites it in place (idempotent) instead of
    scattering run.trace.trace.json."""
    for suffix in (".spans.jsonl", ".trace.json"):
        if path.endswith(suffix):
            return path[:-len(suffix)] + ".trace.json"
    return path.rsplit(".", 1)[0] + ".trace.json"


def cmd_export(args) -> int:
    spans, other = load_spans(args.file)
    out = args.out or _default_out(args.file)
    with open(out, "w") as f:
        json.dump(spans_to_chrome(spans, other), f)
    print("cxn-trace: %d spans -> %s (open in https://ui.perfetto.dev "
          "or chrome://tracing)" % (len(spans), out))
    return 0


def cmd_summary(args) -> int:
    spans, _ = load_spans(args.file)
    roots = [s for s in spans
             if s["name"] == "request" and s["tid"] >= REQ_TID_BASE]
    roots.sort(key=lambda s: -s["dur"])
    print("%d spans, %d requests" % (len(spans), len(roots)))
    if roots:
        print("\nslowest %d requests:" % min(args.top, len(roots)))
        print("  %-8s %10s %-9s %8s %8s" % ("rid", "total_ms", "status",
                                            "prompt", "tokens"))
        for s in roots[:args.top]:
            a = s["args"]
            print("  %-8s %10.1f %-9s %8s %8s"
                  % (a.get("rid", s["tid"] - REQ_TID_BASE),
                     s["dur"] * 1e3, a.get("status", "?"),
                     a.get("prompt_tokens", "-"), a.get("tokens", "-")))
    phases: Dict[str, List[float]] = {}
    for s in spans:
        if s["name"] != "request":
            phases.setdefault(s["name"], []).append(s["dur"])
    if phases:
        print("\nper-phase breakdown:")
        print("  %-16s %7s %12s %10s %10s" % ("phase", "count",
                                              "total_ms", "mean_ms",
                                              "max_ms"))
        for name in sorted(phases, key=lambda n: -sum(phases[n])):
            v = phases[name]
            print("  %-16s %7d %12.1f %10.3f %10.3f"
                  % (name, len(v), sum(v) * 1e3,
                     sum(v) / len(v) * 1e3, max(v) * 1e3))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="cxn-trace", description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)
    ex = sub.add_parser("export", help="span JSONL -> Chrome-trace JSON")
    ex.add_argument("file")
    ex.add_argument("-o", "--out", default="")
    ex.set_defaults(fn=cmd_export)
    sm = sub.add_parser("summary", help="top-N slowest requests + "
                                        "per-phase breakdown")
    sm.add_argument("file")
    sm.add_argument("--top", type=int, default=10)
    sm.set_defaults(fn=cmd_summary)
    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
