"""MoE dispatch benchmark: dense one-hot vs sort-based, top-1 vs top-2.

Reproduces the doc/performance.md "MoE dispatch" table: fwd+bwd of
switch_moe on one chip, S=16384 tokens, D=1024, H=2048, bf16 weights,
capacity_factor 1.25 (host-fetch barrier; 15 warm steps). The measurement
cell itself lives in bench.py (moe_dispatch_cell) so the headline metric
and this analysis table share one definition.

Usage: python tools/moe_bench.py [S=16384]
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from bench import moe_dispatch_cell  # noqa: E402


def main() -> int:
    S = int(sys.argv[1]) if len(sys.argv) > 1 else 16384
    D, H = 1024, 2048
    for e in (2, 4, 8, 32, 64):
        for disp, k in (("dense", 1), ("sort", 1), ("sort", 2),
                        ("ragged", 1), ("ragged", 2)):
            if disp == "dense" and e == 64:
                continue        # dense one-hot is long out of the race
            dt = moe_dispatch_cell(S, D, H, e, disp, k)
            print("E=%2d %-6s top%d: %7.2f ms fwd+bwd (S=%d D=%d H=%d)"
                  % (e, disp, k, dt * 1e3, S, D, H), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
