"""MoE dispatch benchmark: dense one-hot vs sort-based, top-1 vs top-2.

Reproduces the doc/performance.md "MoE dispatch" table: fwd+bwd of
switch_moe on one chip, S=16384 tokens, D=1024, H=2048, bf16 weights,
capacity_factor 1.25 (host-fetch barrier; 15 warm steps).

Usage: python tools/moe_bench.py [S=16384]
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def main() -> int:
    import jax
    import jax.numpy as jnp
    from cxxnet_tpu.ops.moe import switch_moe

    S = int(sys.argv[1]) if len(sys.argv) > 1 else 16384
    D, H = 1024, 2048
    rs = np.random.RandomState(0)
    for e in (2, 4, 8, 32):
        wg = jnp.asarray(rs.randn(D, e).astype(np.float32) * 0.02)
        wu = jnp.asarray(rs.randn(e, D, H).astype(np.float32)
                         * 0.02).astype(jnp.bfloat16)
        wd = jnp.asarray(rs.randn(e, H, D).astype(np.float32)
                         * 0.02).astype(jnp.bfloat16)
        x = jnp.asarray(rs.randn(S, D).astype(np.float32)).astype(jnp.bfloat16)
        for disp, k in (("dense", 1), ("sort", 1), ("sort", 2)):
            def loss(xx, g, u, dn, _disp=disp, _k=k):
                out, aux = switch_moe(xx, g, u, dn, 1.25, dispatch=_disp,
                                      top_k=_k)
                return jnp.sum(out.astype(jnp.float32) ** 2) + aux
            f = jax.jit(jax.value_and_grad(loss, argnums=(0, 2, 3)))
            r = f(x, wg, wu, wd)
            float(r[0])              # host fetch: the true barrier
            t0 = time.time()
            for _ in range(15):
                r = f(x, wg, wu, wd)
            float(r[0])
            dt = (time.time() - t0) / 15
            print("E=%2d %-5s top%d: %7.2f ms fwd+bwd (S=%d D=%d H=%d)"
                  % (e, disp, k, dt * 1e3, S, D, H), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
