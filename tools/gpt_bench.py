"""GPT flagship step benchmark: steady-state step time, tok/s and MFU.

The MFU accounting is strict "model FLOPs" (useful work only):

- param FLOPs / token = 6 * N_params   (fwd 2N + bwd 4N; embedding matmuls
  are inside N, gather cost ignored)
- attention FLOPs / sequence / layer = 6 * n^2 * f * causal(0.5) = 3*n^2*f
  (QK^T and PV are 2*n^2*f each full; causal halves; bwd is 2x fwd)
- remat recompute is NOT credited: recomputed FLOPs are overhead, so a
  rematerialized run must be faster in wall-clock to score the same MFU.

Peak is the v5e bf16 MXU rate (197 TFLOP/s) unless --peak-tflops is given.

Usage:
  python tools/gpt_bench.py --layers 24 --heads 16 --feat 1024 \
      --batch 16 --seq 1024 --bf16 --remat --adam --steps 20
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def count_params(tree):
    import jax
    return sum(x.size for x in jax.tree.leaves(tree))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--seq", type=int, default=1024)
    ap.add_argument("--layers", type=int, default=24)
    ap.add_argument("--heads", type=int, default=16)
    ap.add_argument("--feat", type=int, default=1024)
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--microbatch", type=int, default=1)
    ap.add_argument("--pp", type=int, default=1)
    ap.add_argument("--sp", type=int, default=1)
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--bf16", action="store_true")
    ap.add_argument("--adam", action="store_true")
    ap.add_argument("--remat", action="store_true")
    ap.add_argument("--remat-mode", default="block",
                    choices=["block", "attn_saved"])
    ap.add_argument("--attn-layout", default="auto",
                    choices=["auto", "bnhd", "bhnd"],
                    help="kernel-boundary layout (auto: bhnd iff "
                         "head_dim >= 128; composes with both sp modes)")
    ap.add_argument("--peak-tflops", type=float, default=197.0,
                    help="bf16 peak of one chip (v5e default)")
    ap.add_argument("--trace-dir", default="",
                    help="write an XPlane trace of 3 steps here")
    args = ap.parse_args()

    import jax
    import numpy as np

    from cxxnet_tpu.models.gpt import (GPTConfig, gpt_data_sharding,
                                       gpt_init, gpt_opt_init, gpt_place,
                                       make_train_step)
    from cxxnet_tpu.parallel.mesh import make_mesh

    cfg = GPTConfig(vocab_size=args.vocab, seq_len=args.seq,
                    n_layer=args.layers, n_head=args.heads, feat=args.feat,
                    n_microbatch=args.microbatch,
                    dtype="bfloat16" if args.bf16 else "float32",
                    remat=args.remat, remat_mode=args.remat_mode,
                    attn_layout=args.attn_layout)
    mesh = make_mesh(devices=jax.devices(), pipeline_parallel=args.pp,
                     seq_parallel=args.sp, model_parallel=args.tp)
    params = gpt_place(gpt_init(jax.random.PRNGKey(0), cfg), mesh)
    n_params = count_params(params)
    opt = gpt_opt_init(params, mesh, "adam" if args.adam else "sgd")
    step = make_train_step(cfg, mesh, eta=1e-4,
                           optimizer="adam" if args.adam else "sgd")

    rng = np.random.RandomState(0)
    ids = jax.device_put(
        rng.randint(0, args.vocab, (args.batch, args.seq)).astype(np.int32),
        gpt_data_sharding(mesh))

    t0 = time.time()
    for _ in range(args.warmup):
        params, opt, loss = step(params, opt, ids)
    float(loss)     # host fetch: the only true barrier on tunneled backends
    print("warmup (incl. compile): %.1f s" % (time.time() - t0))

    t0 = time.time()
    for _ in range(args.steps):
        params, opt, loss = step(params, opt, ids)
    float(loss)     # single host fetch barriers the whole chained run
    dt = (time.time() - t0) / args.steps

    if args.trace_dir:
        with jax.profiler.trace(args.trace_dir):
            for _ in range(3):
                params, opt, loss = step(params, opt, ids)
            jax.block_until_ready(loss)

    from bench import gpt_model_flops   # the one FLOPs/MFU definition
    tokens = args.batch * args.seq
    param_fl = 6.0 * n_params * tokens
    total_fl = gpt_model_flops(n_params, args.batch, args.seq, args.feat,
                               args.layers)
    peak = args.peak_tflops * 1e12
    mfu_p = param_fl / dt / peak
    mfu_t = total_fl / dt / peak
    print("params: %.1fM  loss=%.4f" % (n_params / 1e6, float(loss)))
    print("step: %.1f ms   tok/s: %.0f" % (dt * 1e3, tokens / dt))
    print("MFU (param FLOPs): %.1f%%   MFU (param+attn, no remat credit): "
          "%.1f%%" % (100 * mfu_p, 100 * mfu_t))
    return 0


if __name__ == "__main__":
    sys.exit(main())
