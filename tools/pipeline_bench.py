"""End-to-end input-pipeline benchmark: the real imgbin chain feeding the
real jitted AlexNet train step (VERDICT r1 item 3 — the number bench.py's
device-resident mode deliberately excludes).

Builds a synthetic JPEG imgbin dataset (256x256 source, 227 crop, quality 90), then:

1. pipeline-only line rate (`test_io` role) at decode_threads=1/2/4;
2. the AlexNet train step fed by the pipeline through the threadbuffer
   prefetcher, reporting step throughput and the StepStats data-wait
   fraction vs the device-resident rate.

Usage: python tools/pipeline_bench.py [n_images=512 batch=128]
(Results in doc/performance.md; run on the TPU VM. NB this VM exposes ONE
host core — the decode pool cannot scale here; the per-core rate is the
number a real 100+-core TPU host multiplies.)
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def build_dataset(root: str, n: int, src: int = 256) -> str:
    import io as _io
    from PIL import Image
    from cxxnet_tpu.io.binpage import BinaryPageWriter
    os.makedirs(root, exist_ok=True)
    lst = os.path.join(root, "train.lst")
    binp = os.path.join(root, "train.bin")
    rs = np.random.RandomState(0)
    with open(lst, "w") as f, BinaryPageWriter(binp) as w:
        for i in range(n):
            # photo-like statistics (low-pass noise), not uniform noise:
            # raw noise maxes out the Huffman entropy decode, which
            # scale_denom cannot reduce, and inflates every decode cost
            # ~4x vs natural images — the wrong thing to benchmark
            from scipy import ndimage as _ndi
            arr = rs.randint(0, 256, (src, src, 3)).astype(np.float32)
            arr = _ndi.gaussian_filter(arr, (src / 64.0, src / 64.0, 0))
            arr = ((arr - arr.min()) / (np.ptp(arr) + 1e-9)
                   * 255).astype(np.uint8)
            buf = _io.BytesIO()
            Image.fromarray(arr).save(buf, format="JPEG", quality=90)
            w.push(buf.getvalue())
            f.write("%d\t%d\t%06d.jpg\n" % (i, i % 10, i))
    return root


def make_iter(root: str, batch: int, threads: int, target: int = 227,
              decode_at_scale: int = 0):
    from cxxnet_tpu.io import create_iterator
    return create_iterator([
        ("iter", "imgbin"),
        ("image_list", os.path.join(root, "train.lst")),
        ("image_bin", os.path.join(root, "train.bin")),
        ("input_shape", "3,%d,%d" % (target, target)),
        ("rand_crop", "1"), ("rand_mirror", "1"),
        ("decode_at_scale", str(decode_at_scale)),
        ("decode_threads", str(threads)),
        ("iter", "threadbuffer"),
        ("batch_size", str(batch)),
        ("round_batch", "1"),
        ("silent", "1"),
    ])


def pipeline_rate(root: str, batch: int, threads: int, n_batches: int,
                  target: int = 227, decode_at_scale: int = 0) -> float:
    it = make_iter(root, batch, threads, target, decode_at_scale)
    it.before_first()
    it.next()                      # exclude warmup/first-fill
    t0 = time.perf_counter()
    done = 0
    while done < n_batches and it.next():
        done += 1
    dt = time.perf_counter() - t0
    if hasattr(it, "close"):
        it.close()                 # stop prefetch/decode threads before
    return done * batch / dt       # the next timed measurement


def train_with_pipeline(root: str, batch: int, threads: int,
                        n_steps: int = 8):
    import jax
    from cxxnet_tpu import Net
    from cxxnet_tpu.models import alexnet_config
    from cxxnet_tpu.utils.config import tokenize
    from cxxnet_tpu.utils.profiler import StepStats

    net = Net(tokenize(alexnet_config(batch_size=batch, dev="",
                                      precision="bfloat16")))
    net.init_model()
    it = make_iter(root, batch, threads)
    stats = StepStats(batch_size=batch)
    it.before_first()
    # warm compile
    assert it.next()
    net.update(it.value())
    jax.block_until_ready(net.params)
    done = 0
    t0 = time.perf_counter()
    while done < n_steps:
        with stats.phase("data"):
            if not it.next():
                it.before_first()
                continue
        with stats.phase("step"):
            net.update(it.value())
        stats.end_step()
        done += 1
    jax.block_until_ready(net.params)
    dt = time.perf_counter() - t0
    if hasattr(it, "close"):
        it.close()
    totals = stats.phase_totals()
    data_s = totals.get("data", 0.0)
    step_s = totals.get("step", 0.0)
    print("pipeline-fed train: %.0f img/s over %d steps "
          "(data-wait %.0f%%, dispatch %.0f%%)"
          % (done * batch / dt, done, 100 * data_s / dt, 100 * step_s / dt),
          flush=True)


def main() -> int:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 512
    batch = int(sys.argv[2]) if len(sys.argv) > 2 else 128
    root = build_dataset("/tmp/cxn_pipe_bench", n)
    for threads in (1, 2, 4):
        r = pipeline_rate(root, batch, threads, n_batches=max(2, n // batch - 1))
        print("pipeline-only rate, decode_threads=%d: %.0f img/s"
              % (threads, r), flush=True)
    # decode-at-scale scenarios (one decode thread = per-core number):
    # a target at or below half the source engages the libjpeg
    # scale_denom DCT decode (256 -> 112 at 1/2 scale; 512 -> 227 at 1/2)
    nb = max(2, n // batch - 1)
    for src, target in ((256, 112), (512, 227)):
        r2 = build_dataset("/tmp/cxn_pipe_bench_%d" % src, n, src=src)
        off = pipeline_rate(r2, batch, 1, nb, target=target,
                            decode_at_scale=0)
        on = pipeline_rate(r2, batch, 1, nb, target=target,
                           decode_at_scale=1)
        print("decode-at-scale %dpx src -> %d crop, 1 thread: "
              "off %.0f img/s, on %.0f img/s (%.2fx)"
              % (src, target, off, on, on / max(off, 1e-9)), flush=True)
    train_with_pipeline(root, batch, threads=4)
    return 0


if __name__ == "__main__":
    sys.exit(main())
