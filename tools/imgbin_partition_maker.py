#!/usr/bin/env python
"""Shard a .lst file into N partitions and pack each with im2bin — the
multi-file/distributed dataset layout (reference:
tools/imgbin-partition-maker.py, which emitted a Makefile; this version does
the work directly).

Usage: python tools/imgbin_partition_maker.py image.lst image_root out_prefix N [--shuffle]
Produces out_prefix{1..N}.lst / out_prefix{1..N}.bin for
``image_conf_prefix = out_prefix`` + ``image_conf_ids = 1-N``.
"""

import os
import random
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from cxxnet_tpu.io.binpage import BinaryPageWriter  # noqa: E402
from cxxnet_tpu.io.imgbin import parse_list_line  # noqa: E402


def main(argv):
    if len(argv) < 5:
        sys.stderr.write("Usage: imgbin_partition_maker.py image.lst "
                         "image_root out_prefix N [--shuffle]\n")
        return 1
    lst, root, prefix, n = argv[1], argv[2], argv[3], int(argv[4])
    shuffle = "--shuffle" in argv[5:]
    with open(lst) as f:
        lines = [l for l in f if parse_list_line(l) is not None]
    if shuffle:
        random.Random(10).shuffle(lines)
    per = (len(lines) + n - 1) // n
    for i in range(n):
        part = lines[i * per:(i + 1) * per]
        with open("%s%d.lst" % (prefix, i + 1), "w") as f:
            f.writelines(part)
        with BinaryPageWriter("%s%d.bin" % (prefix, i + 1)) as w:
            for line in part:
                parts = parse_list_line(line)
                with open(os.path.join(root, parts[-1]), "rb") as img:
                    w.push(img.read())
        print("partition %d/%d: %d images" % (i + 1, n, len(part)))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
