"""Multi-epoch real-image-pipeline convergence check (CIFAR-10 stand-in).

The north star (BASELINE.md) is convergence parity on real ImageNet; the
strongest in-repo evidence so far was UCI-digits MLP convergence plus the
50-step torch loss differential. This tool closes the remaining gap to the
extent this environment allows: **no natural-image dataset exists on this
machine and egress is zero** (CIFAR-10 cannot be fetched; checked round 4),
so it procedurally generates a hard 10-class 32x32 color dataset and runs
the FULL reference-shaped path on it:

    JPEG files + .lst -> im2bin BinaryPage pack -> imgbin iterator ->
    augmentation (random crop 36->32 + mirror + mean subtraction) ->
    threadbuffer -> AlexNet-style net with the ImageNet.conf quirk set
    (grouped convs + LRN + dropout) -> multi-epoch SGD with lr schedule.

The classes are ten shapes, drawn with a randomly-textured fill at random
position/scale, random fg/bg colors, sensor noise, JPEG-compressed — a
linear model is also trained and must stay far from the CNN (shape classes
at random positions/colors are not linearly separable), so the CNN's
accuracy is earned by representation learning, not prototype matching.
Pinned target: >= 80% top-1 (the verdict r3 #4 bar).

Usage:
  python tools/synth_convergence.py            # full run (TPU, ~6 min)
  python tools/synth_convergence.py --smoke    # tiny/fast (CI, CPU ok)
"""

import argparse
import io as _io
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def _texture(rs, size, kind, c0, c1):
    """Stripe or checker texture image (size x size x 3) between two colors."""
    yy, xx = np.mgrid[0:size, 0:size].astype(np.float32)
    freq = rs.uniform(1.0, 1.6)
    phase = rs.uniform(0, 6.28)
    ang = rs.uniform(0, np.pi)
    t = xx * np.cos(ang) + yy * np.sin(ang)
    if kind == 0:                       # stripes
        m = (np.sin(t * freq + phase) > 0).astype(np.float32)
    else:                               # checker
        u = xx * np.cos(ang + np.pi / 2) + yy * np.sin(ang + np.pi / 2)
        m = ((np.sin(t * freq + phase) > 0)
             ^ (np.sin(u * freq + phase) > 0)).astype(np.float32)
    return m[..., None] * c1 + (1 - m[..., None]) * c0


def _shape_mask(rs, size, kind):
    """Filled mask for one of TEN shapes at random position/scale. The
    class signal is the shape alone — v1 of this dataset split each shape
    into stripes-vs-checker texture classes, which measured near-
    unlearnable at 32px after JPEG+noise (CNN plateaued at ~50% = perfect
    shape / random texture); shapes alone are cleanly learnable."""
    cy, cx = rs.uniform(12, size - 12, 2)
    r = rs.uniform(8.0, 12.0)
    yy, xx = np.mgrid[0:size, 0:size].astype(np.float32)
    dy, dx = yy - cy, xx - cx
    ad, bd = np.abs(dy), np.abs(dx)
    rr = dy * dy + dx * dx
    if kind == 0:                       # disk
        return rr <= r * r
    if kind == 1:                       # ring
        return (rr <= r * r) & (rr >= (r * 0.6) ** 2)
    if kind == 2:                       # square (axis-aligned)
        return (ad <= r * 0.85) & (bd <= r * 0.85)
    if kind == 3:                       # hollow square
        return ((ad <= r * 0.85) & (bd <= r * 0.85)
                & ((ad >= r * 0.5) | (bd >= r * 0.5)))
    if kind == 4:                       # triangle (upward)
        return (dy <= r * 0.8) & (dy >= -r * 0.8) \
            & (bd <= (dy + r * 0.8) * 0.6)
    if kind == 5:                       # triangle (downward)
        return (dy <= r * 0.8) & (dy >= -r * 0.8) \
            & (bd <= (r * 0.8 - dy) * 0.6)
    if kind == 6:                       # plus cross
        return ((ad <= r * 0.3) & (bd <= r)) | ((bd <= r * 0.3) & (ad <= r))
    if kind == 7:                       # X (diagonal cross)
        return (np.abs(dy - dx) <= r * 0.42) & (ad <= r) & (bd <= r) \
            | (np.abs(dy + dx) <= r * 0.42) & (ad <= r) & (bd <= r)
    if kind == 8:                       # horizontal bar
        return (ad <= r * 0.3) & (bd <= r)
    return (bd <= r * 0.3) & (ad <= r)  # vertical bar


def gen_dataset(root, n_train, n_test, size=36, seed=7):
    """Write JPEGs + .lst files; label = shape kind (10 shapes)."""
    from PIL import Image
    rs = np.random.RandomState(seed)
    os.makedirs(os.path.join(root, "img"), exist_ok=True)

    def make(n, lst_name, tag):
        lines = []
        for i in range(n):
            label = rs.randint(0, 10)
            shape_k, tex_k = label, rs.randint(0, 2)   # texture: nuisance
            # background and foreground colors with guaranteed separation
            c0 = rs.uniform(0, 255, 3).astype(np.float32)
            c1 = rs.uniform(0, 255, 3).astype(np.float32)
            while np.abs(c1 - c0).sum() < 180:
                c1 = rs.uniform(0, 255, 3).astype(np.float32)
            bg = np.ones((size, size, 3), np.float32) * c0
            fg = _texture(rs, size, tex_k, c0 * 0.3 + c1 * 0.7, c1)
            mask = _shape_mask(rs, size, shape_k)[..., None]
            img = np.where(mask, fg, bg)
            img += rs.randn(size, size, 3) * 12.0       # sensor noise
            img = np.clip(img, 0, 255).astype(np.uint8)
            rel = "img/%s_%05d.jpg" % (tag, i)
            Image.fromarray(img).save(os.path.join(root, rel), quality=85)
            lines.append("%d\t%d\t%s\n" % (i, label, rel))
        with open(os.path.join(root, lst_name), "w") as f:
            f.writelines(lines)

    make(n_train, "train.lst", "tr")
    make(n_test, "test.lst", "te")


def pack(root, lst, out):
    from cxxnet_tpu.io.binpage import BinaryPageWriter
    from cxxnet_tpu.io.imgbin import parse_list_line
    w = BinaryPageWriter(os.path.join(root, out))
    with open(os.path.join(root, lst)) as f:
        for line in f:
            parts = parse_list_line(line)
            if parts is None:
                continue
            with open(os.path.join(root, parts[-1]), "rb") as img:
                w.push(img.read())
    w.close()


CNN_NET = """
netconfig=start
layer[+1:c1] = conv:conv1
  kernel_size = 5
  pad = 2
  nchannel = 64
  random_type = kaiming
layer[+1] = relu
layer[+1] = lrn
  local_size = 5
  alpha = 0.0001
  beta = 0.75
layer[+1] = max_pooling
  kernel_size = 3
  stride = 2
layer[+1:c2] = conv:conv2
  kernel_size = 5
  pad = 2
  nchannel = 128
  ngroup = 2
  random_type = kaiming
layer[+1] = relu
layer[+1] = lrn
  local_size = 5
  alpha = 0.0001
  beta = 0.75
layer[+1] = max_pooling
  kernel_size = 3
  stride = 2
layer[+1:c3] = conv:conv3
  kernel_size = 3
  pad = 1
  nchannel = 256
  random_type = kaiming
layer[+1] = relu
layer[+1:c4] = conv:conv4
  kernel_size = 3
  pad = 1
  nchannel = 256
  ngroup = 2
  random_type = kaiming
layer[+1] = relu
layer[+1] = max_pooling
  kernel_size = 3
  stride = 2
layer[+1] = flatten
layer[+1:f1] = fullc:fc1
  nhidden = 512
  random_type = kaiming
layer[+1] = relu
layer[+0] = dropout
  threshold = 0.5
layer[+1:f2] = fullc:fc2
  nhidden = 10
  init_sigma = 0.01
layer[+0] = softmax
netconfig=end
"""

LINEAR_NET = """
netconfig=start
layer[+1] = flatten
layer[+1:f1] = fullc:fc1
  nhidden = 10
  init_sigma = 0.01
layer[+0] = softmax
netconfig=end
"""


def conf_text(root, net, rounds, batch, eta, dev, crop):
    return """
data = train
iter = imgbin
    image_list = "{root}/train.lst"
    image_bin = "{root}/train.bin"
    shuffle = 1
    rand_crop = 1
    rand_mirror = 1
    mean_value = 127,127,127
    divideby = 58
iter = threadbuffer
iter = end
eval = test
iter = imgbin
    image_list = "{root}/test.lst"
    image_bin = "{root}/test.bin"
    mean_value = 127,127,127
    divideby = 58
    round_batch = 1
iter = end
{net}
input_shape = 3,{crop},{crop}
batch_size = {batch}
dev = {dev}
precision = bfloat16
num_round = {rounds}
max_round = {rounds}
save_model = 0
train_eval = 1
eval_train = 1
random_type = gaussian
eta = {eta}
lr_schedule = expdecay
lr_gamma = 0.85
lr_step = 2
momentum = 0.9
wd = 0.0005
metric = error
print_step = 1000
""".format(root=root, net=net, rounds=rounds, batch=batch, eta=eta,
           dev=dev, crop=crop)


def run_task(conf_path):
    """Run the CLI LearnTask; return the per-round test-error trace."""
    import re
    import contextlib
    from cxxnet_tpu.cli import LearnTask
    buf = _io.StringIO()
    with contextlib.redirect_stderr(buf):
        rc = LearnTask().run([conf_path])
    assert rc == 0, "training failed"
    trace = []
    for line in buf.getvalue().splitlines():
        m = re.match(r"\[(\d+)\].*test-error:([0-9.]+)", line)
        if m:
            trace.append((int(m.group(1)), float(m.group(2))))
    return trace


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="tiny sizes, 2 rounds (CI / CPU)")
    ap.add_argument("--root", default="",
                    help="dataset dir (default: fresh temp dir)")
    ap.add_argument("--dev", default="tpu")
    args = ap.parse_args()

    n_train, n_test, rounds, batch = 6000, 2000, 14, 128
    if args.smoke:
        n_train, n_test, rounds, batch = 256, 64, 2, 32

    root = args.root or tempfile.mkdtemp(prefix="cxn_synth_")
    if not os.path.exists(os.path.join(root, "train.bin")):
        print("generating %d+%d synthetic 36x36 JPEGs under %s ..."
              % (n_train, n_test, root))
        gen_dataset(root, n_train, n_test)
        pack(root, "train.lst", "train.bin")
        pack(root, "test.lst", "test.bin")

    cnn_conf = os.path.join(root, "cnn.conf")
    lin_conf = os.path.join(root, "linear.conf")
    with open(cnn_conf, "w") as f:
        f.write(conf_text(root, CNN_NET, rounds, batch, 0.05, args.dev, 32))
    with open(lin_conf, "w") as f:
        f.write(conf_text(root, LINEAR_NET, max(rounds // 3, 2), batch,
                          0.02, args.dev, 32))

    print("training AlexNet-style CNN (groups+LRN+dropout), %d rounds ..."
          % rounds)
    cnn = run_task(cnn_conf)
    print("training linear baseline ...")
    lin = run_task(lin_conf)

    print("\nper-round test error (CNN):")
    for r, e in cnn:
        print("  [%2d] %.4f" % (r, e))
    cnn_final = min(e for _, e in cnn[-3:])
    lin_final = min(e for _, e in lin)
    print("\nCNN final test top-1: %.1f%%   linear baseline: %.1f%%"
          % (100 * (1 - cnn_final), 100 * (1 - lin_final)))
    if not args.smoke:
        assert cnn_final <= 0.20, \
            "CNN did not reach 80%% top-1 (err %.3f)" % cnn_final
        assert lin_final >= cnn_final + 0.15, \
            "dataset too easy: linear %.3f vs cnn %.3f" % (lin_final,
                                                           cnn_final)
        print("PASS: >=80%% top-1 through the full imgbin+augment pipeline, "
              "linear gap %.1f pts" % (100 * (lin_final - cnn_final)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
