#!/usr/bin/env python
"""cxn-lint CI driver: lint config files (and optionally their compiled
steps) from the command line.

    python tools/cxn_lint.py <config> [<config> ...] [k=v ...]
    python tools/cxn_lint.py --all-examples
    python tools/cxn_lint.py --compile <config>
    python tools/cxn_lint.py --threads

``--all-examples`` lints every ``example/**/*.conf`` (pass 1 only — no
data files or devices are needed, so this is the fast tier-1 CI check;
tests/test_lint.py wires it into pytest). ``--compile`` additionally
builds the net (init_model on the default backend) and audits the
compiled steps (pass 2: donation aliasing, dtype promotion, host
transfers, collectives); for a GPT-shaped config it also audits the
serve engine's executables — the PAGED chunk-prefill / tick (and
``serve_verify_chunk`` when ``spec_mode`` != off) programs with
abstract block-table inputs by default, or the dense prefill / chunk /
tick set under ``serve_paged=0`` — the programs ``task=serve`` runs,
with the block pool's donation aliasing pinned. Quantized configs
(``serve_int8_weights=1`` / ``serve_kv_dtype=int8``) audit the int8
variants themselves: aliasing on every (values, scales) leaf, plus the
CXN209 no-silent-f32-promotion check on bf16 compute. Under
``serve_int4_weights=1`` additionally audits the packed-nibble
programs: the engine streams the uint8-packed weight planes, the
``int4=`` column reports whether any executable materializes an
unpacked int4 weight image in HBM (CXN211 where the fused
dequant-matmul should be active), and CXN209 covers the i4/u8 ->
f32 promotion variant. Under
``serve_lora=name:path;...`` the audit arms the adapter pool (missing
adapter files are stubbed at the registry's shapes — the audit needs
geometry, not weights) and audits the LoRA-ARMED executables: the
chunk-prefill / tick / verify programs carry the traced adapter-id
operand and the factor-pool leaves, so donation aliasing (the KV pool
still aliases through the extra operands), the CXN208 clip-fold, and
CXN209 promotion-cleanliness are pinned for the programs a multi-LoRA
``task=serve`` actually runs. Under
``serve_tp=N`` the audit builds the model-axis mesh and audits the
PARTITIONED executables — including the shard_map-wrapped fused
paged-attention programs (armed in Pallas interpret mode off-TPU when
the LOCAL head slice's geometry would resolve fused on a real TPU),
so donation aliasing, the zero-all-reduce decode contract, and the
CXN208 clip-fold are pinned for the programs a sharded ``task=serve``
actually runs. A ``serve_block_size=auto`` config resolves through
the tuned-geometry winner (``aot_cache=DIR`` / ``CXN_AOT_CACHE``)
exactly as the production server would before sizing the pool. Every
audited step's line now reports its AOT lower+compile seconds, and
``lint_compile_budget_s=<s>`` turns that into a CI gate: any step
compiling over the budget fails the lint with CXN207, so compile-time
regressions are caught the same way collective-count regressions are.
``k=v`` args are CLI-style overrides linted as line-less pairs.

``--threads`` runs pass 3 — the CXN3xx concurrency lint — over the
installed ``cxxnet_tpu`` package source: ``# guarded_by:`` write
discipline (CXN301), lock-acquisition-order cycles (CXN302), blocking
calls under a lock (CXN303), unjoinable non-daemon threads (CXN304),
and untimed ``Condition.wait`` outside a predicate loop (CXN305). Like
``--all-examples`` it needs no data files or devices (pure AST), so
tests/test_lint.py wires it into the tier-1 gate. It composes with
config paths (both passes run) or stands alone.

Exit codes: 0 clean (warnings allowed), 1 lint errors, 2 usage error.
"""

from __future__ import annotations

import glob
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def lint_one(path, overrides, do_compile=False, verbose=True) -> int:
    from cxxnet_tpu.analysis import audit_net, lint_config_file
    result = lint_config_file(path, extra_pairs=overrides)
    report = result.report
    if do_compile and report.ok():
        # reuse the CLI's section routing for the trainer config
        from cxxnet_tpu.cli import LearnTask
        from cxxnet_tpu.nnet.net import Net
        from cxxnet_tpu.utils.config import load_config
        task = LearnTask()
        for n, v in load_config(path):
            task.set_param(n, v)
        for n, v in overrides:
            task.set_param(n, v)
        net = Net(task._trainer_cfg())
        net.init_model()
        audit_report, infos = audit_net(net)
        report.extend(audit_report.findings)
        # GPT-shaped configs get the serving executables audited too —
        # prefill, the chunk-prefill step, and the decode tick are the
        # programs task=serve actually runs, and their donation aliasing
        # is a different contract from the train steps'. Only the
        # export's own "not GPT-shaped" verdict (ConfigError) skips the
        # audit; any other failure propagates so a broken export cannot
        # silently drop the serve audit while CI stays green.
        try:
            from cxxnet_tpu.nnet.lm import net_gpt_export
            from cxxnet_tpu.utils.config import ConfigError
            gcfg, gparams = net_gpt_export(net)
        except ConfigError:
            gcfg = None
            if verbose:
                print("  (not GPT-shaped: serve-engine audit skipped)")
        if gcfg is not None:
            from cxxnet_tpu.analysis import audit_serve_engine
            from cxxnet_tpu.serve.engine import (DecodeEngine,
                                                 auto_num_blocks)
            # abstract engine: the audit AOT-lowers against
            # ShapeDtypeStruct caches, so no KV pool is allocated for a
            # lint step that never executes anything. The engine
            # mirrors the config's serving mode — paged by default, so
            # the audited programs (block-table gather/scatter, pool
            # donation aliasing) are the ones task=serve actually runs.
            # TP-sharded serve audit (serve_tp > 1): build the model-
            # axis mesh over the local devices and audit the PARTITIONED
            # executables — real mesh shardings on the abstract inputs,
            # donation aliasing and collective counts of the programs a
            # sharded task=serve actually runs. On CPU CI export
            # XLA_FLAGS=--xla_force_host_platform_device_count=<N>
            # before invoking this tool (tests/conftest.py does the
            # same for the suite).
            import jax as _jax
            tp = int(getattr(task, "serve_tp", 0) or 0)
            mesh = None
            if tp > 1:
                devs = _jax.devices()
                if len(devs) < tp:
                    print("cxn-lint: serve_tp=%d needs %d devices, "
                          "found %d — set XLA_FLAGS=--xla_force_host_"
                          "platform_device_count=%d before jax "
                          "initializes" % (tp, tp, len(devs), tp),
                          file=sys.stderr)
                    return 2
                from cxxnet_tpu.parallel.mesh import make_mesh
                mesh = make_mesh(devices=devs[:tp], model_parallel=tp)
            # serve_block_size=auto (-1): resolve through the tuned-
            # geometry winner exactly as the production server would,
            # so the audited executables carry the geometry a warm
            # startup actually builds (miss -> chunk default, 0)
            aot_dir = getattr(task, "aot_cache", "") \
                or os.environ.get("CXN_AOT_CACHE", "")
            serve_bs = int(task.serve_block_size)
            if serve_bs < 0 and task.serve_paged \
                    and task.serve_prefill_chunk > 0:
                from cxxnet_tpu.serve.engine import (resolve_block_size,
                                                     weight_stream_tag)
                serve_bs = resolve_block_size(
                    gcfg, task.serve_prefill_chunk, serve_bs,
                    kv_dtype=task.serve_kv_dtype, tp=max(1, tp),
                    aot=aot_dir or None,
                    weights=weight_stream_tag(
                        bool(task.serve_int8_weights),
                        bool(task.serve_int4_weights),
                        int(task.serve_int4_group)))
            nb = 0
            if task.serve_paged and task.serve_prefill_chunk > 0:
                nb = (task.serve_num_blocks or auto_num_blocks(
                    gcfg, task.serve_slots, task.serve_prefill_chunk,
                    block_size=serve_bs,
                    prefix_mb=task.serve_prefix_mb,
                    kv_mb=task.serve_kv_mb,
                    kv_dtype=task.serve_kv_dtype))
            # serve_lora=name:path;... : audit the LoRA-ARMED programs
            # (traced adapter-id operand + factor-pool leaves). Adapter
            # files that don't exist at lint time are stubbed at the
            # registry's shapes — the audit pins program structure, not
            # adapter weights.
            lora_pool = None
            if getattr(task, "serve_lora", "") and nb > 0:
                from cxxnet_tpu.serve.lora import (AdapterPool,
                                                   make_adapter,
                                                   parse_lora_spec)
                lreg = parse_lora_spec(task.serve_lora)
                lrank = int(getattr(task, "serve_lora_rank", 8))
                stubs = {name: make_adapter(gcfg, lrank)
                         for name, p in lreg.items()
                         if not os.path.exists(p)}
                lora_pool = AdapterPool(
                    gcfg, lreg, rank=lrank,
                    pool_mb=float(getattr(task, "serve_lora_pool_mb",
                                          0.0)),
                    adapters=stubs or None)
            # fused-attention audit off-TPU: the production default is
            # the fused Pallas tick/verify, but the kernel only
            # compiles on TPU backends — arm interpret mode for the
            # audit so CI (the CPU mesh) still AOT-lowers and pins THE
            # FUSED programs' donation aliasing, not a gather stand-in.
            # Only for geometries a real TPU would resolve fused
            # (resident OR streaming), though: interpret mode waives
            # the kernel's geometry limits, and auditing a fused
            # program production would fall back from pins the wrong
            # executable. Under TP the gate reads the LOCAL head slice
            # (n_head // tp) — the shard_map-wrapped kernel audits the
            # same way the sharded engine resolves it.
            from cxxnet_tpu.ops import pallas_kernels as _pk
            geom_ok = False
            if nb > 0:
                from cxxnet_tpu.serve.engine import _paged_geometry
                _, bs_, _, bpr_, _ = _paged_geometry(
                    gcfg, task.serve_prefill_chunk, serve_bs)
                itemsize = 1 if task.serve_kv_dtype == "int8" \
                    else (2 if gcfg.dtype == "bfloat16" else 4)
                lheads = gcfg.n_head // max(1, tp)
                hd = gcfg.feat // gcfg.n_head
                geom_ok = (_pk.paged_attention_geometry_ok(
                               lheads, bpr_, bs_, hd, itemsize)
                           or _pk.paged_attention_streaming_ok(
                               lheads, bpr_, bs_, hd, itemsize))
            arm = bool(geom_ok and task.serve_fused_attn
                       and os.environ.get("CXN_FUSED_ATTN", "1") != "0"
                       and _jax.default_backend() != "tpu"
                       and not _pk._INTERPRET)
            if arm and verbose:
                print("  (fused paged attention audited in Pallas "
                      "interpret mode on this backend)")
            old_interp = _pk._INTERPRET
            try:
                if arm:
                    _pk._INTERPRET = True
                # quantized serve audit (serve_int8_weights /
                # serve_kv_dtype=int8): the abstract engine carries the
                # int8 block dict and the (values, scales) pool structs,
                # so the audited executables ARE the quantized programs
                # — donation aliasing pinned, and CXN209 asserts no
                # silent f32 promotion of the int8 operands (bf16)
                eng = DecodeEngine(gcfg, gparams, slots=2,
                                   prefill_chunk=task.serve_prefill_chunk,
                                   abstract=True,
                                   num_blocks=nb,
                                   block_size=serve_bs,
                                   spec_len=(task.spec_len
                                             if task.spec_mode != "off"
                                             else 0),
                                   fused_attn=bool(task.serve_fused_attn),
                                   mesh=mesh,
                                   int8_weights=bool(
                                       task.serve_int8_weights),
                                   int4_weights=bool(
                                       task.serve_int4_weights),
                                   int4_group=int(
                                       task.serve_int4_group),
                                   kv_dtype=task.serve_kv_dtype,
                                   lora_pool=lora_pool)
                # the serve executables ride under the same compile-time
                # budget as the trainer steps (CXN207): pass
                # lint_compile_budget_s=<s> to gate compile regressions
                # in CI the way lint_collective_budget gates collectives
                # — and, sharded, under the same collective budget
                # (CXN204) the trainer's partitioned steps use
                cbudget = getattr(net, "lint_compile_budget_s", 0.0) \
                    or None
                colbudget = getattr(net, "lint_collective_budget", -1)
                serve_report, serve_infos = audit_serve_engine(
                    eng, compile_budget_s=cbudget,
                    collective_budget=(colbudget if colbudget >= 0
                                       else None))
            finally:
                _pk._INTERPRET = old_interp
            report.extend(serve_report.findings)
            infos += serve_infos
            # AOT-artifact validator (aot_cache=DIR / CXN_AOT_CACHE):
            # audit the CACHED serve executables — the programs a warm
            # production startup actually loads — and fail on CXN210
            # staleness (a config/mesh/jax-version drift that was not
            # followed by re-warming the cache). The validator engine
            # mirrors PRODUCTION sizing (serve_slots, the same
            # auto-sized pool) and production fused/gather resolution
            # (no interpret arming: the artifacts were written by the
            # real backend's resolution), so its keys are the server's.
            if aot_dir:
                from cxxnet_tpu.analysis.step_audit import \
                    audit_aot_artifacts
                veng = DecodeEngine(
                    gcfg, gparams, slots=task.serve_slots,
                    prefill_chunk=task.serve_prefill_chunk,
                    abstract=True, num_blocks=nb,
                    block_size=serve_bs,
                    spec_len=(task.spec_len if task.spec_mode != "off"
                              else 0),
                    fused_attn=bool(task.serve_fused_attn), mesh=mesh,
                    int8_weights=bool(task.serve_int8_weights),
                    int4_weights=bool(task.serve_int4_weights),
                    int4_group=int(task.serve_int4_group),
                    kv_dtype=task.serve_kv_dtype, lora_pool=lora_pool)
                aot_report, aot_infos = audit_aot_artifacts(
                    veng, aot_dir,
                    collective_budget=(colbudget if colbudget >= 0
                                       else None))
                report.extend(aot_report.findings)
                if verbose:
                    for info in aot_infos:
                        print("  aot[%s]: %s" % (info.get("aot", "?"),
                                                 info["label"]))
                infos += [i for i in aot_infos if i.get("aot") == "ok"]
        if verbose:
            from cxxnet_tpu.analysis import format_step_info
            for info in infos:
                print("  %s" % format_step_info(info))
    if verbose or not report.ok():
        print("== %s" % path)
        print(report.format())
    return report.exit_code()


def lint_threads_pass(verbose=True) -> int:
    """Pass 3 over the package tree (no config needed — pure AST)."""
    from cxxnet_tpu.analysis import lint_threads
    from cxxnet_tpu.analysis.findings import LintReport
    report = LintReport()
    lint_threads(report=report)
    if verbose or not report.ok():
        print("== cxxnet_tpu (threads)")
        print(report.format())
    return report.exit_code()


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    do_compile = "--compile" in argv
    all_examples = "--all-examples" in argv
    do_threads = "--threads" in argv
    quiet = "--quiet" in argv
    argv = [a for a in argv
            if a not in ("--compile", "--all-examples", "--threads",
                         "--quiet")]
    overrides = []
    paths = []
    for a in argv:
        if "=" in a and not os.path.exists(a):
            k, v = a.split("=", 1)
            overrides.append((k, v))
        else:
            paths.append(a)
    if all_examples:
        paths += sorted(glob.glob(os.path.join(_REPO, "example", "*",
                                               "*.conf")))
    if not paths and not do_threads:
        print(__doc__.strip(), file=sys.stderr)
        return 2
    rc = 0
    if do_threads:
        rc |= lint_threads_pass(verbose=not quiet)
    for p in paths:
        if not os.path.exists(p):
            print("cannot open config %r" % p, file=sys.stderr)
            return 2
        rc |= lint_one(p, overrides, do_compile=do_compile,
                       verbose=not quiet)
    if not quiet:
        what = "%d config(s)" % len(paths) if paths else "threads pass"
        if paths and do_threads:
            what += " + threads pass"
        print("cxn-lint: %s, %s" % (what, "clean" if rc == 0
                                    else "FAILED"))
    return rc


if __name__ == "__main__":
    sys.exit(main())
