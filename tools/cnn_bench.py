"""CNN model-zoo step benchmark with an XPlane op profile.

The round-3 verdict's open question: ResNet-50 (~2,450 img/s, ~15% MFU) and
Inception-BN (~4,600, ~14%) never got the roofline treatment AlexNet and GPT
did. This harness times the jitted train step device-resident (same protocol
as bench.py — the host link here is a tunnel no framework should be charged
for) and, with --op-profile, traces a few steps and prints the top device
ops by self-time from the XPlane, so "where does the step go" is one command.

MFU accounting: training FLOPs = 3x forward conv/matmul FLOPs (bwd-data +
bwd-filter each cost one forward). Forward FLOPs are counted analytically
from the netconfig graph shapes (2*K*K*Cin/g*Cout*OH*OW per conv output
position; 2*M*N*K per fullc).

Usage:
  python tools/cnn_bench.py --model resnet50 --batch 256 --steps 30
  python tools/cnn_bench.py --model resnet50 --op-profile
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

os.environ.setdefault("LIBTPU_INIT_ARGS",
                      "--xla_tpu_scoped_vmem_limit_kib=65536")


def model_config(name: str, batch: int):
    from cxxnet_tpu.models import (alexnet_config, inception_bn_config,
                                   resnet_config, vgg16_config)
    if name == "resnet50":
        return resnet_config(50, batch_size=batch, dev="")
    if name == "resnet101":
        return resnet_config(101, batch_size=batch, dev="")
    if name == "inception":
        return inception_bn_config(batch_size=batch, dev="")
    if name == "vgg16":
        return vgg16_config(batch_size=batch, dev="")
    if name == "alexnet":
        return alexnet_config(batch_size=batch, dev="")
    raise SystemExit("unknown model %r" % name)


def analytic_train_flops(net, batch: int) -> float:
    """3x forward conv/fullc MACs*2, from the graph's inferred shapes."""
    fwd = 0.0
    for spec, layer in zip(net.graph.layers, net.layers):
        t = layer.type_name
        if t == "conv":
            p = layer.param
            cin = layer.in_channel
            cout, oy, ox = net.node_shapes[spec.outputs[0]]
            fwd += (2.0 * p.kernel_height * p.kernel_width
                    * (cin / p.num_group) * cout * oy * ox) * batch
        elif t == "fullc":
            c, y, x = net.node_shapes[spec.inputs[0]]
            nh = net.node_shapes[spec.outputs[0]][2]
            fwd += 2.0 * c * y * x * nh * batch
    return 3.0 * fwd


def top_ops_from_xplane(trace_dir: str, top: int = 18):
    """Parse the newest xplane.pb under trace_dir; return rows of
    (self_time_us, occurrences, category, op_name)."""
    import glob
    paths = sorted(glob.glob(os.path.join(
        trace_dir, "**", "*.xplane.pb"), recursive=True),
        key=os.path.getmtime)
    if not paths:
        return None, "no xplane.pb under %s" % trace_dir
    from xprof.convert import raw_to_tool_data
    data, _ = raw_to_tool_data.xspace_to_tool_data(
        [paths[-1]], "framework_op_stats", {})
    if isinstance(data, bytes):
        data = data.decode()
    table = json.loads(data)[0]
    cols = [c["id"] for c in table["cols"]]
    out = []
    for row in table["rows"]:
        d = dict(zip(cols, [c.get("v") for c in row["c"]]))
        if d.get("host_or_device") != "Device":
            continue
        out.append((float(d.get("total_self_time") or 0),
                    int(d.get("occurrences") or 0),
                    "%s/%s int=%.1f bw=%.0fGB/s" % (
                        d.get("type", ""), d.get("bound_by", ""),
                        float(d.get("operational_intensity") or 0),
                        float(d.get("measured_memory_bw") or 0)),
                    d.get("operation", "")))
    out.sort(reverse=True)
    return out[:top], None


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet50")
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--warmup", type=int, default=3)
    # (both must be >=1: warmup compiles, steps divide the elapsed time)
    ap.add_argument("--op-profile", action="store_true",
                    help="trace 3 steps and print top device ops")
    ap.add_argument("--trace-dir", default="/tmp/cxn_cnn_trace")
    ap.add_argument("--peak-tflops", type=float, default=197.0)
    ap.add_argument("--f32", action="store_true",
                    help="feed f32 batches (default bf16)")
    args = ap.parse_args()
    if args.steps < 1 or args.warmup < 1:
        ap.error("--steps and --warmup must be >= 1")

    import jax
    from bench import prepare_cnn, run_steps    # the one measurement protocol

    net, step_args = prepare_cnn(model_config(args.model, args.batch),
                                 args.batch, f32_feed=args.f32)
    run_steps(net, step_args, args.warmup)
    dt = run_steps(net, step_args, args.steps)

    step_ms = dt / args.steps * 1e3
    img_s = args.steps * args.batch / dt
    tf = analytic_train_flops(net, args.batch)
    mfu = tf / (dt / args.steps) / (args.peak_tflops * 1e12)
    print(json.dumps({
        "model": args.model, "batch": args.batch,
        "step_ms": round(step_ms, 2),
        "images_per_sec": round(img_s, 1),
        "train_tflops_per_step": round(tf / 1e12, 3),
        "mfu": round(mfu, 4),
    }))

    if args.op_profile:
        import shutil
        shutil.rmtree(args.trace_dir, ignore_errors=True)
        with jax.profiler.trace(args.trace_dir):
            run_steps(net, step_args, 3)
        rows, err = top_ops_from_xplane(args.trace_dir)
        if err:
            print("op-profile error:", err, file=sys.stderr)
            return 1
        total = sum(r[0] for r in rows) if rows else 0.0
        print("\n top device ops by self time (3 steps):")
        for t_us, occ, cat, op in rows:
            print("  %10.0f us  x%-5d %-22s %s" % (t_us, occ, cat, op[:90]))
        print("  (top-%d sum: %.1f ms over 3 steps)" % (len(rows), total / 1e3))
    return 0


if __name__ == "__main__":
    sys.exit(main())
