"""Batch-1 KV-cache decode benchmark: fused whole-step kernel vs XLA scan.

The round-3 analysis pinned batch-1 decode as per-layer-dispatch +
O(cache)-scan bound and named the fused kernel as the fix; this measures
it (CXN_FUSED_DECODE=1 default vs =0 for the unfused A/B). The
measurement cell itself lives in bench.py (decode_cell) so the headline
metric and this A/B harness share one definition.

Usage: python tools/decode_bench.py [--layers 12 --heads 12 --feat 768]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# the fused whole-step decode kernel keeps a layer's bf16 weights + caches
# resident in VMEM. 64 MB is fastest for the 85M shapes (96 MB measured
# -18% there); the 303M batched cells need
# LIBTPU_INIT_ARGS=--xla_tpu_scoped_vmem_limit_kib=98304 (gpt_decode
# falls back to the XLA scan with a notice when the budget is short)
os.environ.setdefault("LIBTPU_INIT_ARGS",
                      "--xla_tpu_scoped_vmem_limit_kib=65536")

from bench import decode_cell  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--layers", type=int, default=12)
    ap.add_argument("--heads", type=int, default=12)
    ap.add_argument("--feat", type=int, default=768)
    ap.add_argument("--seq", type=int, default=1024)
    ap.add_argument("--prompt", type=int, default=16)
    ap.add_argument("--batch", type=int, default=1)
    ap.add_argument("--reps", type=int, default=3)
    args = ap.parse_args()

    dt = decode_cell(args.layers, args.heads, args.feat, args.seq,
                     args.prompt, args.batch, args.reps)
    ms_step = dt * 1e3
    agg = args.batch * 1000.0 / ms_step
    print("fused=%s  %dL x %dh x f%d, cache %d, batch %d: %.3f ms/step "
          "(%.0f tok/s aggregate)"
          % (os.environ.get("CXN_FUSED_DECODE", "1"), args.layers,
             args.heads, args.feat, args.seq, args.batch, ms_step, agg))
    return 0


if __name__ == "__main__":
    sys.exit(main())
