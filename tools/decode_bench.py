"""Batch-1 KV-cache decode benchmark: fused whole-step kernel vs XLA scan.

The round-3 analysis pinned batch-1 decode as per-layer-dispatch +
O(cache)-scan bound and named the fused kernel as the fix; this
measures it (CXN_FUSED_DECODE=1 default vs =0 for the unfused A/B).

Usage: python tools/decode_bench.py [--layers 12 --heads 12 --feat 768]
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# the fused per-layer kernel holds a layer's bf16 weights + caches resident
# in VMEM (~20 MB at the 85M shapes) — same setting bench.py uses
os.environ.setdefault("LIBTPU_INIT_ARGS",
                      "--xla_tpu_scoped_vmem_limit_kib=65536")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--layers", type=int, default=12)
    ap.add_argument("--heads", type=int, default=12)
    ap.add_argument("--feat", type=int, default=768)
    ap.add_argument("--seq", type=int, default=1024)
    ap.add_argument("--prompt", type=int, default=16)
    ap.add_argument("--batch", type=int, default=1)
    ap.add_argument("--reps", type=int, default=3)
    args = ap.parse_args()

    import numpy as np
    import jax
    from cxxnet_tpu.models.gpt import GPTConfig, gpt_decode, gpt_init

    cfg = GPTConfig(vocab_size=256, seq_len=args.seq, n_layer=args.layers,
                    n_head=args.heads, feat=args.feat, n_microbatch=1,
                    dtype="bfloat16")
    params = gpt_init(jax.random.PRNGKey(0), cfg)
    rs = np.random.RandomState(0)
    prompt = jax.numpy.asarray(
        rs.randint(0, 256, (args.batch, args.prompt)).astype(np.int32))
    max_new = args.seq - args.prompt

    out = gpt_decode(params, prompt, max_new, cfg)   # compile
    np.asarray(out)
    best = float("inf")
    for _ in range(args.reps):
        t0 = time.perf_counter()
        out = gpt_decode(params, prompt, max_new, cfg)
        np.asarray(out)
        best = min(best, time.perf_counter() - t0)
    ms_tok = best / max_new * 1e3
    print("fused=%s  %dL x %dh x f%d, cache %d: %.3f ms/token (%.0f tok/s)"
          % (os.environ.get("CXN_FUSED_DECODE", "1"), args.layers,
             args.heads, args.feat, args.seq, ms_tok, 1000.0 / ms_tok))
    return 0


if __name__ == "__main__":
    sys.exit(main())
