"""Memory/bubble accounting for the gpipe schedule on the virtual mesh.

Reproduces the pipeline table in doc/multi-device.md: per-config XLA
temp (live activation) memory from compiled.memory_analysis(), the
analytic GPipe bubble (P-1)/(M+P-1), and a CPU step wall time (schedule
shape comparison only -- virtual devices share one host).

Usage: JAX_PLATFORMS=cpu python tools/pp_accounting.py
"""
import os
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"
import time
import numpy as np, jax, jax.numpy as jnp
from cxxnet_tpu.models.gpt import (GPTConfig, gpt_init, gpt_opt_init,
                                   gpt_place, make_train_step)
from cxxnet_tpu.parallel.mesh import make_mesh

def run(pp, mb, remat):
    cfg = GPTConfig(vocab_size=256, seq_len=256, n_layer=8, n_head=8,
                    feat=512, n_microbatch=mb, dtype="float32", remat=remat)
    mesh = make_mesh(devices=jax.devices()[:pp], pipeline_parallel=pp)
    params = gpt_place(gpt_init(jax.random.PRNGKey(0), cfg), mesh)
    opt = gpt_opt_init(params, mesh, "sgd")
    step = make_train_step(cfg, mesh, eta=0.1)
    ids = jnp.zeros((8, 256), jnp.int32)
    lowered = jax.jit(lambda p, o, x: step(p, o, x)).lower(params, opt, ids)
    comp = lowered.compile()
    ma = comp.memory_analysis()
    temp = ma.temp_size_in_bytes / 1e6
    # warm + time a step (CPU wall time: schedule-shape comparison only)
    p, o = params, opt
    p, o, l = comp(p, o, ids); jax.block_until_ready(l)
    t0 = time.perf_counter()
    for _ in range(3):
        p, o, l = comp(p, o, ids)
    jax.block_until_ready(l)
    dt = (time.perf_counter() - t0) / 3
    bubble = (pp - 1) / (mb + pp - 1)
    print("pp%d mb%d remat=%d: temp %7.1f MB  bubble %4.0f%%  step %6.1f ms"
          % (pp, mb, remat, temp, bubble * 100, dt * 1e3), flush=True)

for pp, mb in ((1, 1), (2, 1), (2, 4), (2, 8), (4, 4), (4, 8)):
    for remat in (False, True):
        run(pp, mb, remat)
