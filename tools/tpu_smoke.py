"""On-chip smoke test for TPU-only dispatch paths.

The CPU test suite cannot see code that only runs on a real TPU backend
(``use_pallas()`` gates, Mosaic lowering of the flash kernels, pallas
inside the gpipe shard_map): the GPT seq>=512 path once compiled fine on
CPU and crashed on TPU. Run this after touching kernels, attention
dispatch, or shard_map code:

    python tools/tpu_smoke.py          # ambient env (axon TPU), ~2-3 min

Exit code 0 = every path compiled and executed on the chip.
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def smoke_alexnet():
    from cxxnet_tpu import Net
    from cxxnet_tpu.models import alexnet_config
    from cxxnet_tpu.utils.config import tokenize

    net = Net(tokenize(alexnet_config(batch_size=64, dev="",
                                      precision="bfloat16")))
    net.init_model()
    rs = np.random.RandomState(0)

    class _B:
        data = rs.rand(64, 3, 227, 227).astype(np.float32)
        label = rs.randint(0, 1000, (64, 1)).astype(np.float32)
        extra_data = []
        num_batch_padd = 0

    net.update(_B)
    loss = float(net._last_loss)
    assert np.isfinite(loss), loss
    print("alexnet train step (band-matmul LRN): loss %.3f" % loss)


def smoke_flash_attention():
    import jax
    import jax.numpy as jnp
    from cxxnet_tpu.ops.pallas_kernels import flash_attention
    from cxxnet_tpu.ops.attention import full_attention

    rs = np.random.RandomState(1)
    q, k, v = (jnp.asarray(rs.randn(2, 1024, 4, 64), jnp.bfloat16)
               for _ in range(3))
    ref = full_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, True)
    err = float(jnp.max(jnp.abs(out.astype(jnp.float32)
                                - ref.astype(jnp.float32))))
    assert err < 3e-2, err          # bf16 tolerance
    g = jax.jit(jax.grad(lambda q: flash_attention(q, k, v, True)
                         .astype(jnp.float32).sum()))(q)
    assert np.isfinite(float(jnp.abs(g).max()))
    print("flash attention fwd+bwd kernels @1024: max fwd err %.1e" % err)


def smoke_gpt_long_seq():
    """The path that once crashed TPU-only: flash dispatch inside gpipe."""
    import jax
    from cxxnet_tpu.models.gpt import (GPTConfig, gpt_init, gpt_opt_init,
                                       gpt_place, make_train_step)
    from cxxnet_tpu.parallel.mesh import make_mesh

    cfg = GPTConfig(vocab_size=256, seq_len=512, n_layer=2, n_head=4,
                    feat=256, n_microbatch=2, dtype="bfloat16")
    mesh = make_mesh(devices=jax.devices())
    params = gpt_place(gpt_init(jax.random.PRNGKey(0), cfg), mesh)
    opt = gpt_opt_init(params, mesh, "adam")
    step = make_train_step(cfg, mesh, eta=1e-3, optimizer="adam")
    rs = np.random.RandomState(2)
    ids = jax.numpy.asarray(rs.randint(0, 256, (4, 512)).astype(np.int32))
    params, opt, loss = step(params, opt, ids)
    assert np.isfinite(float(loss)), float(loss)
    print("GPT seq-512 train step (flash in gpipe shard_map): loss %.3f"
          % float(loss))


def smoke_ring_kernels():
    """Ring attention dispatching its chunks to the flash kernels (the
    per-device axis is size 1 on one chip; kernels still lower + run)."""
    import jax
    import jax.numpy as jnp
    from cxxnet_tpu.ops.attention import full_attention, ring_attention
    from cxxnet_tpu.parallel.mesh import make_mesh

    rs = np.random.RandomState(3)
    q, k, v = (jnp.asarray(rs.randn(2, 1024, 4, 64), jnp.bfloat16)
               for _ in range(3))
    mesh = make_mesh(devices=jax.devices())
    out = jax.jit(lambda a, b, c: ring_attention(a, b, c, mesh,
                                                 causal=True))(q, k, v)
    ref = full_attention(q, k, v, causal=True)
    err = float(jnp.max(jnp.abs(out.astype(jnp.float32)
                                - ref.astype(jnp.float32))))
    assert err < 3e-2, err
    g = jax.jit(jax.grad(lambda a: ring_attention(a, k, v, mesh, causal=True)
                         .astype(jnp.float32).sum()))(q)
    assert np.isfinite(float(jnp.abs(g).max()))
    print("ring attention w/ flash chunk kernels @1024: max fwd err %.1e"
          % err)


def smoke_flash_streaming():
    """Sequences past _FLASH_RESIDENT_MAX dispatch to the streaming kernel
    family (K/V blocks on the grid) — must compile and run on-chip."""
    import jax
    import jax.numpy as jnp
    from cxxnet_tpu.ops.pallas_kernels import (_FLASH_RESIDENT_MAX,
                                               flash_attention)

    s = 2 * _FLASH_RESIDENT_MAX
    rs = np.random.RandomState(4)
    q, k, v = (jnp.asarray(rs.randn(1, s, 2, 64), jnp.bfloat16)
               for _ in range(3))
    g = jnp.ones_like(q)
    out, vjp = jax.vjp(lambda q, k, v: flash_attention(q, k, v, True),
                       q, k, v)
    dq, dk, dv = vjp(g)
    for t in (out, dq, dk, dv):
        assert bool(jnp.isfinite(t.astype(jnp.float32)).all())
    print("streaming flash fwd+bwd @%d: OK" % s)


def smoke_pallas_lrn():
    """The opt-in one-pass LRN kernels (CXN_PALLAS_LRN=1) must keep
    compiling under Mosaic and matching the default XLA band path."""
    import jax
    import jax.numpy as jnp
    from cxxnet_tpu.ops.pallas_kernels import _lrn_reference, lrn_fused

    rs = np.random.RandomState(2)
    x = jnp.asarray(rs.rand(64, 7, 7, 96), jnp.bfloat16)
    ref, vjp_ref = jax.vjp(lambda a: _lrn_reference(a, 5, 1e-4, 0.75, 1.0), x)
    out, vjp_out = jax.vjp(lambda a: lrn_fused(a, 5, 1e-4, 0.75, 1.0), x)
    err = float(jnp.max(jnp.abs(out.astype(jnp.float32)
                                - ref.astype(jnp.float32))))
    g = jnp.ones_like(x)
    gerr = float(jnp.max(jnp.abs(vjp_out(g)[0].astype(jnp.float32)
                                 - vjp_ref(g)[0].astype(jnp.float32))))
    assert err < 3e-2 and gerr < 3e-2, (err, gerr)
    print("pallas LRN fwd+bwd kernels: maxdiff %.3g / %.3g" % (err, gerr))


def smoke_decode():
    import jax
    from cxxnet_tpu.models.gpt import (GPTConfig, gpt_decode, gpt_init,
                                       gpt_place)
    from cxxnet_tpu.parallel.mesh import make_mesh

    cfg = GPTConfig(vocab_size=256, seq_len=128, n_layer=2, n_head=4,
                    feat=128, dtype="bfloat16")
    mesh = make_mesh(devices=jax.devices())
    params = gpt_place(gpt_init(jax.random.PRNGKey(0), cfg), mesh)
    prompt = jax.numpy.asarray(np.array([[1, 2, 3]], np.int32))
    out = gpt_decode(params, prompt, 16, cfg, mesh)
    assert out.shape[1] == 3 + 16
    print("KV-cache decode: %d tokens" % out.shape[1])


def smoke_cached_attention():
    """The opt-in single-kernel decode attention (CXN_PALLAS_DECODE=1) must
    keep compiling under Mosaic (no 1-D vector shapes) and match the XLA
    masked-softmax formulation."""
    import jax
    import jax.numpy as jnp
    from cxxnet_tpu.ops.pallas_kernels import cached_attention

    rs = np.random.RandomState(5)
    b, h, s, d = 2, 4, 64, 128
    q = jnp.asarray(rs.randn(b, h, 1, d), jnp.bfloat16)
    ck = jnp.asarray(rs.randn(b, h, s, d), jnp.bfloat16)
    cv = jnp.asarray(rs.randn(b, h, s, d), jnp.bfloat16)
    pos = 17
    out = cached_attention(q, ck, cv, pos)
    qf, kf, vf = (t.astype(jnp.float32) for t in (q, ck, cv))
    sc = jnp.einsum("bhqd,bhsd->bhqs", qf, kf) / (d ** 0.5)
    sc = jnp.where(jnp.arange(s)[None, None, None, :] <= pos, sc, -jnp.inf)
    ref = jnp.einsum("bhqs,bhsd->bhqd", jax.nn.softmax(sc, axis=-1), vf)
    err = float(jnp.max(jnp.abs(out.astype(jnp.float32) - ref)))
    assert err < 3e-2, err
    print("pallas cached-attention decode kernel: maxdiff %.3g" % err)


def smoke_fused_decode():
    """The whole-step decode kernel must keep compiling under Mosaic and
    match the jnp layer-stack math numerically (token-id comparison is
    meaningless on random weights: near-uniform logits flip argmax at
    1-ulp differences). Fixture shared with
    tests/test_pallas_kernels.py::test_fused_decode_step_matches_jnp."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from tests.test_pallas_kernels import make_decode_reference
    from cxxnet_tpu.ops import pallas_kernels as pk

    rs = np.random.RandomState(7)
    blocks, h, ck, cv, pos, nh, reference = make_decode_reference(
        rs, dtype="bfloat16")
    ref_h, _ = jax.jit(reference)(blocks, h)
    out, _, _ = jax.jit(
        lambda bb, hh, c1, c2: pk.fused_decode_step(bb, hh, c1, c2, pos,
                                                    nh))(blocks, h, ck, cv)
    err = float(jnp.max(jnp.abs(out.astype(jnp.float32)
                                - ref_h.astype(jnp.float32))))
    assert err < 0.1, err      # <= a few bf16 ulps at these magnitudes
    print("fused whole-step decode kernel: maxdiff %.3g vs jnp stack"
          % err)


def smoke_int8_decode():
    """int8 weight-streaming decode on real hardware: (a) the kernel fed
    int8+scales equals the kernel fed dequantized weights (Mosaic int8
    load + convert path), (b) a TRAINED model's greedy generation under
    int8 still follows its learned rule and matches bf16 token-for-token
    (the accuracy bar for the opt-in; random weights can't test this —
    near-uniform logits flip argmax at 1-ulp)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from tests.test_pallas_kernels import make_decode_reference
    from cxxnet_tpu.models.gpt import (GPTConfig,
                                       _dequantize_decode_blocks,
                                       _quantize_decode_blocks,
                                       gpt_decode, gpt_init, gpt_opt_init,
                                       gpt_place, make_train_step)
    from cxxnet_tpu.ops import pallas_kernels as pk
    from cxxnet_tpu.parallel.mesh import make_mesh

    rs = np.random.RandomState(7)
    blocks, h, ck, cv, pos, nh, _ = make_decode_reference(
        rs, dtype="bfloat16")
    qb = _quantize_decode_blocks(blocks)
    deq = _dequantize_decode_blocks(qb, dtype=jnp.bfloat16)
    run = jax.jit(lambda bb, hh, c1, c2: pk.fused_decode_step(
        bb, hh, c1, c2, pos, nh))
    out_q, _, _ = run(qb, h, ck, cv)
    out_r, _, _ = run(deq, h, ck, cv)
    err = float(jnp.max(jnp.abs(out_q.astype(jnp.float32)
                                - out_r.astype(jnp.float32))))
    assert err < 0.1, err

    v = 64
    cfg = GPTConfig(vocab_size=v, seq_len=256, n_layer=4, n_head=4,
                    feat=256, dtype="bfloat16", n_microbatch=1)
    mesh = make_mesh(devices=jax.devices())
    params = gpt_place(gpt_init(jax.random.PRNGKey(0), cfg), mesh)
    opt = gpt_opt_init(params, mesh, "adam")
    step = make_train_step(cfg, mesh, eta=3e-3, optimizer="adam")
    for i in range(120):
        start = rs.randint(0, v, (32, 1))
        ids = (start + np.arange(256)) % v
        noise = rs.randint(0, v, ids.shape)
        ids = np.where(rs.rand(*ids.shape) < 0.05, noise, ids)
        params, opt, _ = step(params, opt,
                              jnp.asarray(ids.astype(np.int32)))
    prompt = jnp.asarray((np.arange(8)[None] % v).astype(np.int32))
    out_bf = np.asarray(gpt_decode(params, prompt, 240, cfg))
    out_i8 = np.asarray(gpt_decode(params, prompt, 240, cfg,
                                   int8_weights=True))
    s = out_i8[0]
    rule = float((s[1:] == (s[:-1] + 1) % v).mean())
    agree = float((out_bf == out_i8).mean())
    # the ROBUST accuracy bar is rule-following: whole-sequence agreement
    # under-reports (one early flip diverges an autoregressive run into a
    # different-but-valid continuation), so it is reported, not asserted
    assert rule > 0.99, (rule, agree)
    print("int8 decode: kernel maxdiff %.3g vs dequant; trained-model "
          "rule-following %.3f (asserted), bf16 agreement %.3f "
          "(reported)" % (err, rule, agree))


def main() -> int:
    import jax
    from cxxnet_tpu.ops import pallas_kernels

    backend = jax.default_backend()
    assert backend in ("tpu", "axon") and not pallas_kernels._INTERPRET, (
        "not on a TPU backend (got %r) — this script exists to exercise "
        "TPU-only dispatch paths; exit-0 off-chip would be meaningless"
        % backend)
    t0 = time.time()
    for fn in (smoke_alexnet, smoke_flash_attention, smoke_gpt_long_seq,
               smoke_ring_kernels, smoke_flash_streaming, smoke_pallas_lrn,
               smoke_decode, smoke_cached_attention, smoke_fused_decode,
               smoke_int8_decode):
        fn()
    print("TPU SMOKE OK (%.0fs)" % (time.time() - t0))
    return 0


if __name__ == "__main__":
    sys.exit(main())
