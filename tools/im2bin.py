#!/usr/bin/env python
"""im2bin — pack images listed in a .lst file into a BinaryPage .bin dataset.

Equivalent of the reference tool (/root/reference/tools/im2bin.cpp:1-67);
output is format-compatible with reference .bin files (64MB pages).

Usage: python tools/im2bin.py image.lst image_root_dir output_file
.lst line format: index<TAB>label[<TAB>more labels]<TAB>relative/path.jpg
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from cxxnet_tpu.io.binpage import BinaryPageWriter  # noqa: E402
from cxxnet_tpu.io.imgbin import parse_list_line  # noqa: E402


def main(argv):
    if len(argv) != 4:
        sys.stderr.write(
            "Usage: im2bin.py image.lst image_root_dir output_file\n")
        return 1
    lst, root, out = argv[1], argv[2], argv[3]
    start = time.time()
    print("creating image binary pack from %s..." % lst)
    w = BinaryPageWriter(out)
    with open(lst) as f:
        for line in f:
            parts = parse_list_line(line)
            if parts is None:
                continue
            path = os.path.join(root, parts[-1])
            with open(path, "rb") as img:
                w.push(img.read())
            if w.n_objects % 1000 == 0:
                print("\r[%8d] images processed to %d pages, %d sec elapsed"
                      % (w.n_objects, w.n_pages, int(time.time() - start)),
                      end="")
                sys.stdout.flush()
    w.close()    # flushes the final partial page; n_pages is now exact
    print("\nfinished [%8d] images packed to %d pages, %d sec elapsed"
          % (w.n_objects, w.n_pages, int(time.time() - start)))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
