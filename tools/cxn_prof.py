#!/usr/bin/env python
"""cxn-prof: the device & compiler observatory's CLI
(doc/observability.md).

Roofline mode::

    python tools/cxn_prof.py <config> [k=v ...]

Builds the config's net (random init unless ``model_in=`` is given) and
prints the per-program roofline table — FLOPs, HBM bytes, arithmetic
intensity, peak memory, compile seconds, measured time, MFU and
achieved-bandwidth fraction — for the trainer's four jitted steps and,
for GPT-shaped configs, the serve engine's prefill / prefill-chunk /
verify-chunk / tick programs (``cxxnet_tpu.obs.devprof``; this is a
thin wrapper over ``task=prof``, so the two surfaces cannot drift).
``prof_reps=N`` controls the timing best-of; ``prof_reps=0`` skips
execution entirely (cost model only, no device time).

Diff mode — the bench regression gate::

    python tools/cxn_prof.py --diff OLD.json NEW.json [--tol 0.10]
                             [--cell-tol metric=frac ...]

Compares two bench snapshots (the ``BENCH_rXX.json`` line-per-metric
format bench.py emits) cell by cell with per-cell tolerance bands:
direction comes from each cell's unit (ms / % lines regress UP,
throughput/fraction/ratio lines regress DOWN), the base tolerance is
``--tol`` (default 10%), a cell that records its own best-of ``band``
widens its tolerance by the observed run-to-run spread, and
``--cell-tol`` pins per-cell overrides for known-noisy lines. Exit 1
when any cell regressed beyond its band — the CI gate; identical
snapshots always pass.
"""

from __future__ import annotations

import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

# units where a SMALLER value is better — everything else (tokens/sec,
# images/sec, fraction, ratio) regresses downward
_LOWER_IS_BETTER = ("ms", "ms/token", "%", "sec", "s")

# built-in extra tolerance for cells whose recorded history shows
# run-to-run swings a flat 10% band would flag as phantom regressions
# (doc/performance.md / doc/serving.md record the spreads)
_DEFAULT_CELL_TOL = {
    "moe_dispatch_tokens_per_sec": 0.15,
    "serve_tokens_per_sec": 0.20,
    "serve_p95_ttft_ms": 0.25,
    "serve_p95_ttft_ms_prefill_heavy": 0.25,
    "serve_prefix_hit_tokens_per_sec": 0.20,
    "serve_spec_tokens_per_sec": 0.20,
    "serve_tokens_per_sec_fused": 0.25,     # open-loop serve cell noise;
    #                                         direction comes from the
    #                                         tokens/sec unit (regresses
    #                                         DOWN), band matches the
    #                                         other serve trace cells
    "serve_tokens_per_sec_longctx": 0.25,   # same open-loop trace
    #                                         spread as the fused cell
    #                                         (streaming vs gather arms)
    "autotune_wall_ms": 0.50,               # a compile-and-time sweep
    #                                         on a shared CI core: wall
    #                                         noise like lint_wall_ms
    #                                         (the ms unit regresses UP)
    "serve_tokens_per_sec_tuned": 0.30,     # tiny-geometry trace cell
    #                                         like the tp2/replicated
    #                                         ones: dispatch-bound on
    #                                         CPU
    "serve_tokens_per_mib": 0.20,
    "serve_tokens_per_mib_int8": 0.30,      # preempt/swap-regime trace
    #                                         (the bf16 arm thrashes by
    #                                         design) — swap timing
    #                                         noise on top of the usual
    #                                         open-loop spread
    "gpt_decode_spec_int8_ms_per_token": 0.30,  # spec accept-rate +
    #                                         dequant dispatch jitter
    #                                         (CPU pins machinery, not
    #                                         bandwidth — serving.md)
    "serve_tokens_per_mib_int4": 0.30,      # open-loop trace on shared
    #                                         cores; the metric prices
    #                                         tokens/s per MiB of device
    #                                         working set (KV + packed
    #                                         weight pool), so wall
    #                                         noise lands in the
    #                                         numerator
    "gpt_decode_int4_ms_per_token": 0.30,   # CPU pins the dequant
    #                                         machinery, not HBM
    #                                         bandwidth — dispatch
    #                                         jitter dominates
    "serve_tokens_per_sec_tp2": 0.30,       # tiny-geometry trace cells:
    #                                         dispatch-bound on CPU, so
    "serve_tokens_per_sec_replicated": 0.30,  # scheduler-thread timing
    #                                         noise dominates (round 17)
    "serve_goodput_replicated_kill": 0.10,  # a fraction in [0, 1]: the
    #                                         router replays a killed
    #                                         replica's requests, so
    #                                         this regresses DOWN from
    #                                         ~1.0 only when failover
    #                                         breaks
    "serve_tokens_per_sec_fleet": 0.35,     # cross-process worker pool
    #                                         on shared cores: socket +
    #                                         pickle + process-scheduler
    #                                         noise on top of the tiny-
    #                                         geometry trace (round 18)
    "serve_goodput_fleet_kill": 0.10,       # fraction in [0, 1]: the
    #                                         fleet router replays a
    #                                         SIGKILLed decode worker's
    #                                         journal on the survivor —
    #                                         drops below ~1.0 only
    #                                         when failover breaks
    "serve_goodput_guaranteed_overload": 0.05,  # the guaranteed
    #                                         tenant's completion
    #                                         fraction under 3x
    #                                         overload: pinned ~1.0 —
    #                                         any drop means the SLO
    #                                         isolation broke
    "serve_p95_ttft_ms_guaranteed_overload": 0.30,  # open-loop
    #                                         overload trace on a
    #                                         shared-core rig:
    #                                         scheduler-timing noise
    #                                         dominates (the ms unit
    #                                         regresses UP)
    "serve_tokens_per_sec_lora_mixed": 0.30,  # mixed-adapter open-loop
    #                                         trace on shared cores:
    #                                         tiny-geometry dispatch
    #                                         noise like the tp2/tuned
    #                                         cells (round 20)
    "serve_lora_vs_swap": 0.30,             # batched-vs-sequential-swap
    #                                         speedup ratio: both arms
    #                                         carry the open-loop noise,
    #                                         so the quotient widens —
    #                                         regresses DOWN toward 1.0
    #                                         if one-tick batching stops
    #                                         paying
    "gpt_decode_spec_ms_per_token": 0.20,
    "engine_cold_start_ms": 0.35,           # wall-clock startup cells on
    #                                         a shared CI core: compile/
    #                                         deserialize timing noise
    "engine_recovery_ms": 0.40,             # (the ms unit regresses UP;
    #                                         doc/performance.md "AOT
    #                                         executable cache" records
    #                                         the arms)
    "obs_overhead_pct": 1.0,        # a percentage-point-scale cell:
    #                                 gate it on the <= 2% budget in
    #                                 bench.py, not on relative drift
    "train_feed_overlap": 0.15,
    "lint_wall_ms": 0.50,
    "lint_threads_wall_ms": 0.50,   # same shared-core wall noise band
}


def load_bench(path: str) -> dict:
    """{metric: record} from a bench snapshot. Accepts both shapes the
    repo produces: bench.py's own stdout (one JSON object per line,
    non-metric noise skipped) and the driver-recorded ``BENCH_rXX.json``
    wrapper (one document whose ``tail`` string embeds those lines)."""
    with open(path) as f:
        text = f.read()
    lines = text.splitlines()
    try:
        doc = json.loads(text)
        if isinstance(doc, dict) and isinstance(doc.get("tail"), str):
            lines = doc["tail"].splitlines()
        elif isinstance(doc, dict) and "metric" in doc:
            lines = [text]
    except json.JSONDecodeError:
        pass                        # line-per-metric stdout capture
    out = {}
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and "metric" in rec and "value" in rec:
            out[rec["metric"]] = rec
    if not out:
        raise SystemExit("%s: no bench metric lines found" % path)
    return out


def _band_spread(rec: dict) -> float:
    """Relative run-to-run spread a cell recorded about itself (the
    MoE cell's ``band=[lo, best]``) — 0 when absent."""
    band = rec.get("band")
    if not (isinstance(band, (list, tuple)) and len(band) == 2):
        return 0.0
    lo, hi = sorted(float(b) for b in band)
    return (hi - lo) / hi if hi > 0 else 0.0


def diff_cells(old: dict, new: dict, tol: float = 0.10,
               cell_tol: dict = None) -> tuple:
    """Per-cell comparison; returns (rows, regressions). Each row is
    {metric, old, new, delta, tol, verdict} with verdict one of
    ok | REGRESSED | improved | new | gone."""
    cell_tol = dict(_DEFAULT_CELL_TOL, **(cell_tol or {}))
    rows, regressions = [], []
    for name in sorted(set(old) | set(new)):
        o, n = old.get(name), new.get(name)
        if o is None or n is None:
            rows.append({"metric": name, "old": o and o["value"],
                         "new": n and n["value"], "delta": 0.0,
                         "tol": 0.0, "verdict": "new" if o is None
                         else "gone"})
            continue
        ov, nv = float(o["value"]), float(n["value"])
        lower_better = o.get("unit", "") in _LOWER_IS_BETTER
        # worse-direction relative change; band spread from EITHER
        # snapshot widens the tolerance (the cell itself measured that
        # much noise between best-of reps in one run)
        cell = max(tol, cell_tol.get(name, 0.0)) \
            + 1.5 * max(_band_spread(o), _band_spread(n))
        if ov == 0.0:
            delta = 0.0
        elif lower_better:
            delta = (nv - ov) / abs(ov)
        else:
            delta = (ov - nv) / abs(ov)
        verdict = "ok"
        if delta > cell:
            verdict = "REGRESSED"
            regressions.append(name)
        elif delta < -cell:
            verdict = "improved"
        rows.append({"metric": name, "old": ov, "new": nv,
                     "delta": delta, "tol": cell, "verdict": verdict})
    return rows, regressions


def cmd_diff(old_path: str, new_path: str, tol: float,
             cell_tol: dict) -> int:
    rows, regressions = diff_cells(load_bench(old_path),
                                   load_bench(new_path), tol, cell_tol)
    print("%-36s %12s %12s %8s %6s  %s"
          % ("metric", "old", "new", "delta", "tol", "verdict"))
    for r in rows:
        fmt = lambda v: "-" if v is None else "%.4g" % v
        print("%-36s %12s %12s %7.1f%% %5.0f%%  %s"
              % (r["metric"], fmt(r["old"]), fmt(r["new"]),
                 100 * r["delta"], 100 * r["tol"], r["verdict"]))
    if regressions:
        print("cxn-prof: %d cell(s) REGRESSED beyond tolerance: %s"
              % (len(regressions), ", ".join(regressions)))
        return 1
    print("cxn-prof: no regressions (%d cells compared)"
          % sum(1 for r in rows if r["verdict"] != "new"
                and r["verdict"] != "gone"))
    return 0


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv:
        print(__doc__.strip(), file=sys.stderr)
        return 2
    if "--diff" in argv:
        argv.remove("--diff")
        tol = 0.10
        cell_tol = {}
        if "--tol" in argv:
            i = argv.index("--tol")
            tol = float(argv[i + 1])
            del argv[i:i + 2]
        while "--cell-tol" in argv:
            i = argv.index("--cell-tol")
            k, v = argv[i + 1].split("=", 1)
            cell_tol[k] = float(v)
            del argv[i:i + 2]
        if len(argv) != 2:
            print("cxn-prof --diff needs exactly OLD.json NEW.json",
                  file=sys.stderr)
            return 2
        return cmd_diff(argv[0], argv[1], tol, cell_tol)
    # roofline mode: hand off to the CLI's task=prof (one surface);
    # trailing k=v pairs ride through as overrides
    if not os.path.exists(argv[0]):
        print("cannot open config %r" % argv[0], file=sys.stderr)
        return 2
    from cxxnet_tpu.cli import main as cli_main
    return cli_main([argv[0], "task=prof"] + argv[1:])


if __name__ == "__main__":
    sys.exit(main())
