"""Eval/predict throughput: device-resident forward rate + pipelined
evaluate() overlap.

Two numbers, mirroring bench.py's convention for train:

1. device-resident eval forward (steady state of a prefetching pipeline,
   host-fetch barrier) -> eval img/s to quote next to the train img/s;
2. evaluate() end-to-end through an in-memory iterator — on THIS rig the
   host->device tunnel dominates (same caveat as pipeline-fed train), so
   the interesting part is the overlap structure, not the absolute rate.

Usage: python tools/eval_bench.py [batch=1024]
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

os.environ.setdefault("LIBTPU_INIT_ARGS",
                      "--xla_tpu_scoped_vmem_limit_kib=65536")

import numpy as np


def main() -> int:
    import jax
    from cxxnet_tpu import Net
    from cxxnet_tpu.models import alexnet_config
    from cxxnet_tpu.utils.config import tokenize

    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 1024
    net = Net(tokenize(alexnet_config(batch_size=batch, dev="",
                                      precision="bfloat16")))
    net.init_model()

    rs = np.random.RandomState(0)
    x = rs.rand(batch, 3, 227, 227).astype(np.float32)
    y = rs.randint(0, 1000, (batch, 1)).astype(np.float32)

    class _B:
        data, label, extra_data = x, y, []
        num_batch_padd = 0

    import ml_dtypes
    _B.data = _B.data.astype(ml_dtypes.bfloat16)
    data, extras, _ = net._device_batch(_B())
    uniq = (net._out_node,)

    # 1. device-resident eval forward
    for _ in range(3):
        (out,) = net._jit_forward(net.params, net.states, data, extras, uniq)
    float(np.asarray(out).reshape(-1)[0])   # barrier
    steps = 50
    t0 = time.perf_counter()
    for _ in range(steps):
        (out,) = net._jit_forward(net.params, net.states, data, extras, uniq)
    float(np.asarray(out).reshape(-1)[0])
    dt = time.perf_counter() - t0
    print("device-resident eval forward: %.0f img/s (%.1f ms/batch of %d)"
          % (steps * batch / dt, dt / steps * 1e3, batch))

    # 2. evaluate() end-to-end (tunnel-bound on this rig; shows overlap)
    class MemIter:
        def __init__(self, n):
            self.n = n

        def before_first(self):
            self.i = 0

        def next(self):
            self.i += 1
            return self.i <= self.n

        def value(self):
            return _B()

    net.eval_metrics = net.eval_metrics  # metrics configured by the conf
    it = MemIter(6)
    t0 = time.perf_counter()
    line = net.evaluate(it, "bench")
    dt = time.perf_counter() - t0
    print("evaluate() end-to-end: %.0f img/s over 6 host-fed batches%s"
          % (6 * batch / dt, line))
    return 0


if __name__ == "__main__":
    sys.exit(main())
